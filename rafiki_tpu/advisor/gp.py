"""Minimal, dependency-free Gaussian-process Bayesian optimization core.

Operates purely on the unit cube [0,1]^d; knob-type handling lives in
rafiki_tpu.sdk.knob (each knob encodes itself). Maximizes expected
improvement. Pending (proposed-but-unscored) points are fantasized with the
constant-liar strategy so concurrent proposals spread out instead of
colliding — the coordination the reference lacked entirely.
"""

from __future__ import annotations

import math
from typing import List, Optional

import numpy as np


def _matern52(X1: np.ndarray, X2: np.ndarray, lengthscale: float) -> np.ndarray:
    d = np.sqrt(
        np.maximum(
            ((X1[:, None, :] - X2[None, :, :]) ** 2).sum(-1), 0.0
        )
    )
    r = math.sqrt(5.0) * d / lengthscale
    return (1.0 + r + r * r / 3.0) * np.exp(-r)


class GaussianProcess:
    """GP with Matérn-5/2 kernel, standardized targets, and a small
    marginal-likelihood grid search over the lengthscale."""

    NOISE = 1e-6

    def __init__(self) -> None:
        self.X: Optional[np.ndarray] = None
        self.y: Optional[np.ndarray] = None
        self._chol: Optional[np.ndarray] = None
        self._alpha: Optional[np.ndarray] = None
        self._ls = 0.3
        self._y_mean = 0.0
        self._y_std = 1.0

    def fit(self, X: np.ndarray, y: np.ndarray) -> None:
        self.X = np.asarray(X, dtype=np.float64)
        y = np.asarray(y, dtype=np.float64)
        self._y_mean = float(y.mean())
        self._y_std = float(y.std()) or 1.0
        self.y = (y - self._y_mean) / self._y_std
        best_ll, best_ls = -np.inf, self._ls
        for ls in (0.1, 0.2, 0.3, 0.5, 1.0):
            ll = self._marginal_ll(ls)
            if ll > best_ll:
                best_ll, best_ls = ll, ls
        self._ls = best_ls
        K = _matern52(self.X, self.X, self._ls) + self.NOISE * np.eye(len(self.X))
        self._chol = np.linalg.cholesky(K)
        self._alpha = np.linalg.solve(
            self._chol.T, np.linalg.solve(self._chol, self.y)
        )

    def _marginal_ll(self, ls: float) -> float:
        assert self.X is not None and self.y is not None
        K = _matern52(self.X, self.X, ls) + self.NOISE * np.eye(len(self.X))
        try:
            L = np.linalg.cholesky(K)
        except np.linalg.LinAlgError:
            return -np.inf
        alpha = np.linalg.solve(L.T, np.linalg.solve(L, self.y))
        return float(
            -0.5 * self.y @ alpha - np.log(np.diag(L)).sum()
        )

    def predict(self, Xs: np.ndarray) -> tuple[np.ndarray, np.ndarray]:
        """Posterior mean and stddev at query points (de-standardized)."""
        assert self.X is not None and self._chol is not None
        Ks = _matern52(np.asarray(Xs, dtype=np.float64), self.X, self._ls)
        mu = Ks @ self._alpha
        v = np.linalg.solve(self._chol, Ks.T)
        var = np.maximum(1.0 + self.NOISE - (v * v).sum(0), 1e-12)
        return (
            mu * self._y_std + self._y_mean,
            np.sqrt(var) * self._y_std,
        )


def _norm_pdf(z: np.ndarray) -> np.ndarray:
    return np.exp(-0.5 * z * z) / math.sqrt(2 * math.pi)


def _norm_cdf(z: np.ndarray) -> np.ndarray:
    from math import erf

    return 0.5 * (1.0 + np.vectorize(erf)(z / math.sqrt(2)))


def expected_improvement(
    mu: np.ndarray, sigma: np.ndarray, best: float, xi: float = 0.01
) -> np.ndarray:
    imp = mu - best - xi
    z = imp / sigma
    return imp * _norm_cdf(z) + sigma * _norm_pdf(z)


class BayesOpt:
    """Sequential maximizer over [0,1]^d with pending-point fantasies."""

    N_CANDIDATES = 2048

    def __init__(self, dims: int, seed: int = 0):
        self.dims = dims
        self.rng = np.random.default_rng(seed)
        self.observed_X: List[np.ndarray] = []
        self.observed_y: List[float] = []
        self.pending_X: List[np.ndarray] = []

    @property
    def n_warmup(self) -> int:
        return max(3, self.dims)

    def suggest(self, register_pending: bool = True) -> np.ndarray:
        """Next point to evaluate. Random during warmup; EI afterwards, with
        pending points fantasized at the current minimum (constant liar).

        With ``register_pending=False`` the caller is expected to call
        ``mark_pending`` itself (e.g. after quantizing the point to the knob
        grid, so the later ``observe`` can retire it by value)."""
        if self.dims == 0:
            return np.zeros(0)
        if len(self.observed_X) < self.n_warmup:
            x = self.rng.random(self.dims)
        else:
            X = np.array(self.observed_X)
            y = np.array(self.observed_y)
            if self.pending_X:
                lie = float(y.min())
                X = np.vstack([X, np.array(self.pending_X)])
                y = np.concatenate([y, np.full(len(self.pending_X), lie)])
            gp = GaussianProcess()
            gp.fit(X, y)
            cand = self.rng.random((self.N_CANDIDATES, self.dims))
            # include jittered copies of the incumbent for local refinement
            best_x = self.observed_X[int(np.argmax(self.observed_y))]
            local = np.clip(
                best_x + 0.05 * self.rng.standard_normal((64, self.dims)), 0, 1
            )
            cand = np.vstack([cand, local])
            mu, sigma = gp.predict(cand)
            ei = expected_improvement(mu, sigma, float(np.max(self.observed_y)))
            x = cand[int(np.argmax(ei))]
        if register_pending:
            self.mark_pending(x)
        return x

    def mark_pending(self, x: np.ndarray) -> None:
        self.pending_X.append(np.asarray(x, dtype=np.float64))

    def observe(self, x: np.ndarray, y: float) -> None:
        x = np.asarray(x, dtype=np.float64)
        self.observed_X.append(x)
        self.observed_y.append(float(y))
        # Retire one fantasy per real observation: the nearest pending point.
        # (Feedback may arrive for points proposed elsewhere or quantized to a
        # knob grid, so exact matching would leak fantasies forever.)
        if self.pending_X:
            d = [float(((p - x) ** 2).sum()) for p in self.pending_X]
            self.pending_X.pop(int(np.argmin(d)))
