"""Hyperparameter-optimization advisor (reference rafiki/advisor/).

A native Gaussian-process Bayesian optimizer replaces the reference's
``baytune``/BTB dependency (reference rafiki/advisor/btb_gp_advisor.py). The
advisor is a *library* first — workers use it in-process or through the admin
HTTP API — and one advisor is shared per sub-train-job so parallel trials
coordinate through constant-liar fantasies (the reference spawned an
independent GP per worker, reference rafiki/worker/train.py:213, making
parallel HPO uncoordinated).
"""

from rafiki_tpu.advisor.advisor import Advisor, AdvisorStore, BaseAdvisor, RandomAdvisor  # noqa: F401
