"""ResNet (v1.5 bottleneck / basic-block) for the CIFAR/ImageNet configs.

Backs the BASELINE.json "CIFAR-10 ResNet-50 with advisor Bayesian HPO"
config. NHWC layout, bf16 compute, BatchNorm folded as (scale, bias, moving
stats) with stats updated functionally — params and batch-stats are separate
subtrees so the train step can donate both.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Any, Dict, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp

from rafiki_tpu.models import core

Params = Dict[str, Any]


@dataclass(frozen=True)
class ResNetConfig:
    stage_sizes: Sequence[int] = (3, 4, 6, 3)   # ResNet-50
    bottleneck: bool = True
    width: int = 64
    num_classes: int = 1000
    small_inputs: bool = False  # CIFAR stem: 3x3/1 conv, no maxpool


def resnet18(num_classes: int = 10, small_inputs: bool = True) -> ResNetConfig:
    return ResNetConfig(stage_sizes=(2, 2, 2, 2), bottleneck=False,
                        num_classes=num_classes, small_inputs=small_inputs)


def resnet50(num_classes: int = 1000, small_inputs: bool = False) -> ResNetConfig:
    return ResNetConfig(stage_sizes=(3, 4, 6, 3), bottleneck=True,
                        num_classes=num_classes, small_inputs=small_inputs)


def _bn_init(dim: int) -> Params:
    return {"scale": jnp.ones((dim,), jnp.float32),
            "bias": jnp.zeros((dim,), jnp.float32)}


def _bn_stats_init(dim: int) -> Params:
    return {"mean": jnp.zeros((dim,), jnp.float32),
            "var": jnp.ones((dim,), jnp.float32)}


def _batchnorm(p: Params, stats: Params, x: jax.Array, train: bool,
               momentum: float = 0.9, eps: float = 1e-5
               ) -> Tuple[jax.Array, Params]:
    xf = x.astype(jnp.float32)
    if train:
        mean = jnp.mean(xf, axis=(0, 1, 2))
        var = jnp.var(xf, axis=(0, 1, 2))
        new_stats = {
            "mean": momentum * stats["mean"] + (1 - momentum) * mean,
            "var": momentum * stats["var"] + (1 - momentum) * var,
        }
    else:
        mean, var = stats["mean"], stats["var"]
        new_stats = stats
    y = (xf - mean) * jax.lax.rsqrt(var + eps) * p["scale"] + p["bias"]
    return y.astype(x.dtype), new_stats


def _block_channels(cfg: ResNetConfig, stage: int) -> Tuple[int, int]:
    width = cfg.width * (2 ** stage)
    out = width * 4 if cfg.bottleneck else width
    return width, out


def init(rng: jax.Array, cfg: ResNetConfig) -> Tuple[Params, Params]:
    """Returns (params, batch_stats)."""
    keys = iter(jax.random.split(rng, 1024))
    params: Params = {}
    stats: Params = {}
    stem_k = 3 if cfg.small_inputs else 7
    params["stem"] = core.conv2d_init(next(keys), stem_k, stem_k, 3, cfg.width)
    params["stem_bn"] = _bn_init(cfg.width)
    stats["stem_bn"] = _bn_stats_init(cfg.width)
    cin = cfg.width
    for si, n_blocks in enumerate(cfg.stage_sizes):
        width, cout = _block_channels(cfg, si)
        for bi in range(n_blocks):
            name = f"s{si}b{bi}"
            blk: Params = {}
            bst: Params = {}
            if cfg.bottleneck:
                blk["conv1"] = core.conv2d_init(next(keys), 1, 1, cin, width)
                blk["conv2"] = core.conv2d_init(next(keys), 3, 3, width, width)
                blk["conv3"] = core.conv2d_init(next(keys), 1, 1, width, cout)
                for i, d in (("bn1", width), ("bn2", width), ("bn3", cout)):
                    blk[i] = _bn_init(d)
                    bst[i] = _bn_stats_init(d)
            else:
                blk["conv1"] = core.conv2d_init(next(keys), 3, 3, cin, width)
                blk["conv2"] = core.conv2d_init(next(keys), 3, 3, width, cout)
                for i, d in (("bn1", width), ("bn2", cout)):
                    blk[i] = _bn_init(d)
                    bst[i] = _bn_stats_init(d)
            if cin != cout or (bi == 0 and si > 0):
                blk["proj"] = core.conv2d_init(next(keys), 1, 1, cin, cout)
                blk["proj_bn"] = _bn_init(cout)
                bst["proj_bn"] = _bn_stats_init(cout)
            params[name] = blk
            stats[name] = bst
            cin = cout
    params["head"] = core.dense_init(next(keys), cin, cfg.num_classes)
    return params, stats


def apply(params: Params, stats: Params, images: jax.Array, cfg: ResNetConfig,
          train: bool = False) -> Tuple[jax.Array, Params]:
    """images (B, H, W, 3) -> (logits, new_batch_stats)."""
    new_stats: Params = {}
    x = core.cast_for_compute(images)
    stride = 1 if cfg.small_inputs else 2
    x = core.conv2d(params["stem"], x, stride=stride)
    x, new_stats["stem_bn"] = _batchnorm(
        params["stem_bn"], stats["stem_bn"], x, train)
    x = jax.nn.relu(x)
    if not cfg.small_inputs:
        x = jax.lax.reduce_window(
            x, -jnp.inf, jax.lax.max, (1, 3, 3, 1), (1, 2, 2, 1), "SAME")
    for si, n_blocks in enumerate(cfg.stage_sizes):
        for bi in range(n_blocks):
            name = f"s{si}b{bi}"
            blk, bst = params[name], stats[name]
            nst: Params = {}
            stride = 2 if (bi == 0 and si > 0) else 1
            residual = x
            if cfg.bottleneck:
                y = core.conv2d(blk["conv1"], x)
                y, nst["bn1"] = _batchnorm(blk["bn1"], bst["bn1"], y, train)
                y = jax.nn.relu(y)
                y = core.conv2d(blk["conv2"], y, stride=stride)
                y, nst["bn2"] = _batchnorm(blk["bn2"], bst["bn2"], y, train)
                y = jax.nn.relu(y)
                y = core.conv2d(blk["conv3"], y)
                y, nst["bn3"] = _batchnorm(blk["bn3"], bst["bn3"], y, train)
            else:
                y = core.conv2d(blk["conv1"], x, stride=stride)
                y, nst["bn1"] = _batchnorm(blk["bn1"], bst["bn1"], y, train)
                y = jax.nn.relu(y)
                y = core.conv2d(blk["conv2"], y)
                y, nst["bn2"] = _batchnorm(blk["bn2"], bst["bn2"], y, train)
            if "proj" in blk:
                residual = core.conv2d(blk["proj"], x, stride=stride)
                residual, nst["proj_bn"] = _batchnorm(
                    blk["proj_bn"], bst["proj_bn"], residual, train)
            x = jax.nn.relu(y + residual)
            new_stats[name] = nst
    x = jnp.mean(x, axis=(1, 2))
    logits = core.dense(params["head"], x).astype(jnp.float32)
    return logits, new_stats
