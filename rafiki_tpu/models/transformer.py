"""Shared pre-LN transformer stack, scan-over-layers, sharding-annotated.

The layer stack is a single pytree whose leaves carry a leading ``depth``
axis (models/core.py ``stack_layers``), consumed by ``lax.scan`` — one
compiled block body regardless of depth. Partition specs shard:

- attention heads and MLP hidden over the ``model`` (TP) axis,
- the scanned ``depth`` axis over the ``pipe`` axis when pipeline parallelism
  is on (parallel/pipeline.py),
- activations batch over ``data`` and sequence over ``seq`` (SP).

This stack is what ViT/BERT instantiate; the reference has no transformer
at all (its deepest model is a TF1 ProGAN, reference pg_gans.py), so this
subsystem is part of the BASELINE.json north-star configs (ViT-B/16,
BERT-base) rather than a port.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Any, Dict, Optional, Tuple

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from rafiki_tpu.models import core
from rafiki_tpu.ops.attention import attention_init, multi_head_attention

Params = Dict[str, Any]


@dataclass(frozen=True)
class TransformerConfig:
    dim: int = 768
    depth: int = 12
    heads: int = 12
    mlp_ratio: int = 4
    dropout: float = 0.0
    causal: bool = False
    # None = auto: flash once the (S,S) score tensors would crowd HBM
    # (ops/attention.py FLASH_SCORES_BYTES); XLA's fused attention is
    # faster below that
    use_flash: Optional[bool] = None
    moe_experts: int = 0  # >0 replaces the MLP with an expert-parallel MoE
    moe_capacity_factor: float = 1.25
    # "ring" routes attention through parallel/ring.py when the current mesh
    # has a seq axis > 1: exact attention with k/v shards rotating over ICI,
    # sequence length scaling linearly in chips. None = GSPMD seq-sharding
    # of activations only (all-gather on the attention matmuls).
    seq_parallel: Optional[str] = None
    # Rematerialization of the scanned block body (the memory knob that lets
    # large batches fit HBM — without it lax.scan saves every layer's
    # activations for backward, ~0.4 GB/layer for ViT-B at batch 128):
    #   None   — save everything (fastest when it fits),
    #   "dots" — jax.checkpoint_policies.dots_with_no_batch_dims_saveable:
    #            projection/MLP matmul outputs are saved, attention scores
    #            and elementwise ops recomputed (the PaLM recipe — near-zero
    #            extra MXU work, (S,S) score tensors never saved),
    #   "full" — save only each block's input; backward re-runs the whole
    #            block forward (~33% extra hardware FLOPs, minimal memory).
    remat: Optional[str] = None
    # "gpipe" runs the depth stack through parallel/pipeline.py microbatch
    # pipelining when the current mesh has a pipe axis > 1: each stage holds
    # depth/n_stages layers, activations hop stage-to-stage over ICI. None =
    # GSPMD weight-sharding of the scanned depth axis.
    pipeline: Optional[str] = None
    n_microbatches: int = 4
    # lax.scan unroll factor for the depth scan: >1 lets XLA fuse and
    # software-pipeline across adjacent blocks (scan bodies compile once
    # and cannot overlap otherwise) at the cost of unroll x compile time.
    # Single-chip throughput knob; numerics identical.
    scan_unroll: int = 1
    # One (BS, D) x (D, 3HDh) matmul for the q/k/v projections (x read
    # from HBM once per layer, one wide MXU gemm) instead of three —
    # runtime weight stack, param layout/checkpoints/TP specs unchanged.
    # Sweep lever (bench_models.py RAFIKI_SWEEP_QKV); same math, low-bit
    # differences only from contraction order.
    fused_qkv: bool = False


def block_init(rng: jax.Array, cfg: TransformerConfig) -> Params:
    from rafiki_tpu.parallel.moe import moe_init

    k_attn, k_mlp1, k_mlp2 = jax.random.split(rng, 3)
    hidden = cfg.dim * cfg.mlp_ratio
    params = {
        "ln1": core.layernorm_init(cfg.dim),
        "attn": attention_init(k_attn, cfg.dim, cfg.heads),
        "ln2": core.layernorm_init(cfg.dim),
    }
    if cfg.moe_experts > 0:
        params["moe"] = moe_init(k_mlp1, cfg.dim, hidden, cfg.moe_experts)
    else:
        params["mlp"] = {
            "w1": core.dense_init(k_mlp1, cfg.dim, hidden),
            "w2": core.dense_init(k_mlp2, hidden, cfg.dim),
        }
    return params


def block_apply(params: Params, x: jax.Array, cfg: TransformerConfig,
                rng: Optional[jax.Array] = None,
                deterministic: bool = True) -> Tuple[jax.Array, jax.Array]:
    """Returns (x, aux_loss) — aux is the MoE load-balancing term (0 for
    dense blocks)."""
    from rafiki_tpu.parallel.moe import moe_apply
    from rafiki_tpu.parallel.sharding import (
        current_mesh,
        mesh_axis_size,
        shard_activations,
    )

    x = shard_activations(x, ("data", "seq", None))
    r1 = r2 = None
    if rng is not None:
        r1, r2 = jax.random.split(rng)
    attn_fn = None
    if cfg.seq_parallel == "ring" and mesh_axis_size("seq") > 1:
        from rafiki_tpu.parallel.ring import ring_attention

        mesh = current_mesh()
        attn_fn = lambda q, k, v, causal: ring_attention(  # noqa: E731
            q, k, v, mesh, causal=causal)
    h = multi_head_attention(params["attn"], core.layernorm(params["ln1"], x),
                             causal=cfg.causal, use_flash=cfg.use_flash,
                             attn_fn=attn_fn, fused_qkv=cfg.fused_qkv)
    x = x + core.dropout(r1, h, cfg.dropout, deterministic)
    h = core.layernorm(params["ln2"], x)
    aux = jnp.zeros((), jnp.float32)
    if cfg.moe_experts > 0:
        h, aux = moe_apply(params["moe"], h, cfg.moe_capacity_factor)
    else:
        h = core.dense(params["mlp"]["w1"], h)
        h = jax.nn.gelu(h)
        h = core.dense(params["mlp"]["w2"], h)
    x = x + core.dropout(r2, h, cfg.dropout, deterministic)
    return x, aux


def stack_init(rng: jax.Array, cfg: TransformerConfig) -> Params:
    keys = jax.random.split(rng, cfg.depth)
    return core.stack_layers([block_init(k, cfg) for k in keys])


def stack_apply(stacked: Params, x: jax.Array, cfg: TransformerConfig,
                rng: Optional[jax.Array] = None,
                deterministic: bool = True) -> Tuple[jax.Array, jax.Array]:
    """scan over the depth-stacked block params -> (x, summed aux loss).

    With ``cfg.pipeline == 'gpipe'`` and a pipe axis > 1 on the current
    mesh, the scan is replaced by microbatch pipelining over the stages
    (parallel/pipeline.py) — each stage holds depth/n_stages layers and
    activations hop over ICI. The gpipe path is deterministic (no dropout
    rng threading across stages) and returns aux = 0.
    """
    from rafiki_tpu.parallel.sharding import (
        activation_mesh,
        current_mesh,
        mesh_axis_size,
    )

    def remat_wrap(fn):
        if cfg.remat == "dots":
            return jax.checkpoint(
                fn,
                policy=jax.checkpoint_policies.dots_with_no_batch_dims_saveable)
        if cfg.remat == "full":
            return jax.checkpoint(fn)
        if cfg.remat is not None:
            raise ValueError(f"unknown remat policy {cfg.remat!r} "
                             "(expected None, 'dots' or 'full')")
        return fn

    if cfg.pipeline == "gpipe" and mesh_axis_size("pipe") > 1:
        from rafiki_tpu.parallel.pipeline import gpipe_apply

        if cfg.moe_experts > 0:
            raise ValueError(
                "pipeline='gpipe' does not support MoE blocks (the stage "
                "body drops the load-balancing aux loss); use GSPMD pipe "
                "weight-sharding (pipeline=None) for MoE models")
        if mesh_axis_size("model") > 1:
            raise ValueError(
                "pipeline='gpipe' cannot combine with a model (TP) axis "
                "> 1: the pipeline shard_map claims stage weights whole, "
                "which would silently all-gather TP-sharded kernels; use "
                "GSPMD pipe weight-sharding (pipeline=None) with TP")
        depth = jax.tree.leaves(stacked)[0].shape[0]
        n_stages = mesh_axis_size("pipe")
        if depth % n_stages != 0:
            raise ValueError(
                f"stack depth {depth} not divisible by {n_stages} pipeline "
                "stages")
        if cfg.dropout > 0 and not deterministic:
            raise ValueError(
                "pipeline='gpipe' is deterministic (no dropout-rng "
                "threading across stages); set dropout=0 or pipeline=None")
        if x.shape[0] % cfg.n_microbatches != 0:
            raise ValueError(
                f"batch {x.shape[0]} not divisible by "
                f"n_microbatches={cfg.n_microbatches}")

        mesh = current_mesh()

        @remat_wrap
        def block_fn(layer, h):
            # plain per-stage compute: no activation sharding constraints or
            # nested shard_maps inside the pipeline's shard_map body
            with activation_mesh(None):
                y, _ = block_apply(layer, h, cfg, None, True)
            return y

        y = gpipe_apply(block_fn, stacked, x, mesh,
                        n_microbatches=cfg.n_microbatches)
        return y, jnp.zeros((), jnp.float32)

    block = remat_wrap(lambda layer, h, sub: block_apply(
        layer, h, cfg, sub, deterministic))

    def body(carry, layer):
        x, key = carry
        sub = None
        if key is not None:
            key, sub = jax.random.split(key)
        y, aux = block(layer, x, sub)
        return (y, key), aux

    (x, _), auxs = jax.lax.scan(body, (x, rng), stacked,
                                unroll=max(cfg.scan_unroll, 1))
    return x, jnp.sum(auxs)


def block_partition_specs(cfg: TransformerConfig, stacked: bool = True) -> Params:
    """PartitionSpecs for one block (or the depth-stacked pytree).

    TP sharding follows the megatron split: column-parallel qkv/w1, row-
    parallel wo/w2 — XLA inserts the psum on the row-parallel matmul's
    output over ICI.
    """
    from rafiki_tpu.parallel.moe import moe_partition_specs

    lead = ("pipe",) if stacked else ()

    def spec(*axes):
        return P(*(lead + axes))

    specs = {
        "ln1": {"scale": spec(None), "bias": spec(None)},
        "attn": {
            "wq": spec(None, "model", None),
            "wk": spec(None, "model", None),
            "wv": spec(None, "model", None),
            "wo": spec("model", None, None),
            "bo": spec(None),
        },
        "ln2": {"scale": spec(None), "bias": spec(None)},
    }
    if cfg.moe_experts > 0:
        specs["moe"] = jax.tree.map(
            lambda s: P(*(lead + tuple(s))), moe_partition_specs(),
            is_leaf=lambda x: isinstance(x, P))
    else:
        specs["mlp"] = {
            "w1": {"kernel": spec(None, "model"), "bias": spec("model")},
            "w2": {"kernel": spec("model", None), "bias": spec(None)},
        }
    return specs
