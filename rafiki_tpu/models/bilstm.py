"""BiLSTM sequence tagger (parity: reference PyBiLstm,
examples/models/pos_tagging/PyBiLstm.py:19-32 — PyTorch BiLSTM for POS
tagging).

The recurrence is a ``lax.scan`` over time with all four gates fused into
one (D, 4H) matmul per step — the XLA-friendly LSTM shape. The bidirectional
pass is the same scan run on the reversed sequence. Padded positions carry a
mask so state stops propagating past sequence end.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Any, Dict, Tuple

import jax
import jax.numpy as jnp

from rafiki_tpu.models import core

Params = Dict[str, Any]


@dataclass(frozen=True)
class BiLstmConfig:
    vocab: int = 10000
    n_tags: int = 50
    embed_dim: int = 64
    hidden: int = 128
    max_len: int = 128


def _lstm_init(rng: jax.Array, in_dim: int, hidden: int) -> Params:
    kx, kh = jax.random.split(rng)
    return {
        "wx": core.xavier_uniform(kx, (in_dim, 4 * hidden)),
        "wh": core.xavier_uniform(kh, (hidden, 4 * hidden)),
        "b": jnp.zeros((4 * hidden,), jnp.float32),
    }


def _lstm_scan(p: Params, x: jax.Array, mask: jax.Array) -> jax.Array:
    """x: (B, T, D), mask: (B, T) -> hidden states (B, T, H)."""
    b, t, _ = x.shape
    h_dim = p["wh"].shape[0]
    xg = jnp.einsum("btd,dg->btg", x, p["wx"].astype(x.dtype)) + p["b"].astype(x.dtype)

    def step(carry, inp):
        h, c = carry
        gates_x, m = inp
        gates = gates_x + jnp.dot(h, p["wh"].astype(h.dtype))
        i, f, g, o = jnp.split(gates, 4, axis=-1)
        i, f, o = jax.nn.sigmoid(i), jax.nn.sigmoid(f), jax.nn.sigmoid(o)
        g = jnp.tanh(g)
        c_new = f * c + i * g
        h_new = o * jnp.tanh(c_new)
        m = m[:, None]
        h = jnp.where(m, h_new, h)
        c = jnp.where(m, c_new, c)
        return (h, c), h

    h0 = jnp.zeros((b, h_dim), x.dtype)
    c0 = jnp.zeros((b, h_dim), x.dtype)
    _, hs = jax.lax.scan(step, (h0, c0),
                         (xg.swapaxes(0, 1), mask.swapaxes(0, 1)))
    return hs.swapaxes(0, 1)


def init(rng: jax.Array, cfg: BiLstmConfig) -> Params:
    ke, kf, kb, kh = jax.random.split(rng, 4)
    return {
        "embed": core.embedding_init(ke, cfg.vocab, cfg.embed_dim),
        "fwd": _lstm_init(kf, cfg.embed_dim, cfg.hidden),
        "bwd": _lstm_init(kb, cfg.embed_dim, cfg.hidden),
        "head": core.dense_init(kh, 2 * cfg.hidden, cfg.n_tags),
    }


def apply(params: Params, ids: jax.Array, mask: jax.Array,
          cfg: BiLstmConfig) -> jax.Array:
    """ids, mask: (B, T) -> per-token tag logits (B, T, n_tags)."""
    x = core.embedding(params["embed"], ids, dtype=jnp.float32)
    h_f = _lstm_scan(params["fwd"], x, mask)
    h_b = _lstm_scan(params["bwd"], x[:, ::-1], mask[:, ::-1])[:, ::-1]
    h = jnp.concatenate([h_f, h_b], axis=-1)
    return core.dense(params["head"], h).astype(jnp.float32)
