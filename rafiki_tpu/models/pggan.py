"""Progressive GAN, re-designed TPU-first.

Capability parity with the reference fork's signature feature — the 1,447-line
TF1 Progressive-GAN mini-framework (reference pg_gans.py:34-1447: `Network`
graph templates :601-1090, multi-GPU `Optimizer` with NCCL all-reduce
:1093-1225, `TrainingSchedule` :1227-1274, WGAN-GP+ACGAN losses :1276-1330) —
with a fundamentally different architecture:

- **No graph surgery.** The reference clones TF graph templates per device and
  re-wires them as resolution grows (pg_gans.py:293-311, :601-670). Here the
  generator/discriminator are pure pytree functions with *static* shapes; the
  level-of-detail (lod) is a traced scalar that cross-fades per-stage RGB
  heads, so growth never retraces. Only the integer "highest active stage"
  is a static argument — at most log2(resolution)-2 recompiles per run,
  each cached by XLA.
- **GSPMD data parallelism.** The reference splits the minibatch across GPUs
  by hand and all-reduces gradients with `tf.contrib.nccl.all_sum`
  (pg_gans.py:1165-1170). Here the train step is jitted over a
  `jax.sharding.Mesh` with the batch sharded on the `data` axis; XLA inserts
  the gradient all-reduce over ICI itself.
- **bf16 compute, f32 params/optimizer.** Matmuls/convs ride the MXU in
  bfloat16; parameters, the generator EMA, and Adam state stay float32.

Components: equalized-learning-rate layers, pixel norm, minibatch stddev,
WGAN-GP + ACGAN losses, generator EMA ("Gs", reference pg_gans.py:730-741),
`training_schedule` (reference :1227-1274 semantics), and `PgganTrainer`
orchestrating the D_repeats/minibatch_repeats loop (reference :328-343).
"""

from __future__ import annotations

import math
from dataclasses import dataclass
from typing import Any, Callable, Dict, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
import optax

Params = Dict[str, Any]


# ---------------------------------------------------------------------------
# config

@dataclass(frozen=True)
class PgganConfig:
    resolution: int = 32          # final output resolution (power of 2, >= 8)
    num_channels: int = 3
    label_size: int = 0           # >0 enables ACGAN conditioning
    latent_size: int = 128
    fmap_base: int = 1024
    fmap_decay: float = 1.0
    fmap_max: int = 128
    gp_lambda: float = 10.0       # WGAN-GP gradient penalty weight
    eps_drift: float = 1e-3       # drift penalty on real scores
    cond_weight: float = 1.0      # ACGAN label-loss weight
    mbstd_group_size: int = 4
    compute_dtype: Any = jnp.bfloat16

    @property
    def num_stages(self) -> int:
        """Stage s renders at 4*2**s; stage 0 is 4x4."""
        return int(math.log2(self.resolution)) - 1

    def nf(self, stage: int) -> int:
        return min(
            int(self.fmap_base / (2.0 ** (stage * self.fmap_decay))),
            self.fmap_max,
        )


# ---------------------------------------------------------------------------
# primitive layers (equalized learning rate: weights are stored N(0,1) and
# rescaled by the He constant at apply time, so Adam's per-parameter scale
# is uniform across layers)

def eq_dense_init(rng: jax.Array, in_dim: int, out_dim: int) -> Params:
    return {"w": jax.random.normal(rng, (in_dim, out_dim), jnp.float32),
            "b": jnp.zeros((out_dim,), jnp.float32)}


def eq_dense(p: Params, x: jax.Array, gain: float = math.sqrt(2.0)) -> jax.Array:
    scale = gain / math.sqrt(p["w"].shape[0])
    return x @ (p["w"] * scale).astype(x.dtype) + p["b"].astype(x.dtype)


def eq_conv_init(rng: jax.Array, k: int, cin: int, cout: int) -> Params:
    return {"w": jax.random.normal(rng, (k, k, cin, cout), jnp.float32),
            "b": jnp.zeros((cout,), jnp.float32)}


def eq_conv(p: Params, x: jax.Array, gain: float = math.sqrt(2.0)) -> jax.Array:
    k, _, cin, _ = p["w"].shape
    scale = gain / math.sqrt(k * k * cin)
    y = jax.lax.conv_general_dilated(
        x, (p["w"] * scale).astype(x.dtype), (1, 1), "SAME",
        dimension_numbers=("NHWC", "HWIO", "NHWC"))
    return y + p["b"].astype(y.dtype)


def pixel_norm(x: jax.Array, eps: float = 1e-8) -> jax.Array:
    return x * jax.lax.rsqrt(jnp.mean(jnp.square(x), axis=-1, keepdims=True) + eps)


def upscale2d(x: jax.Array, factor: int = 2) -> jax.Array:
    n, h, w, c = x.shape
    x = jnp.broadcast_to(x[:, :, None, :, None, :],
                         (n, h, factor, w, factor, c))
    return x.reshape(n, h * factor, w * factor, c)


def downscale2d(x: jax.Array, factor: int = 2) -> jax.Array:
    # reshape-mean avg-pool: unlike reduce_window it supports the
    # second-order autodiff the WGAN gradient penalty needs
    n, h, w, c = x.shape
    x = x.reshape(n, h // factor, factor, w // factor, factor, c)
    return jnp.mean(x, axis=(2, 4))


def minibatch_stddev(x: jax.Array, group_size: int) -> jax.Array:
    """Append one channel of batch-group stddev (mode-collapse detector)."""
    n, h, w, c = x.shape
    g = min(group_size, n)
    while n % g:                               # largest divisor of n <= g
        g -= 1
    y = x.reshape(g, n // g, h, w, c).astype(jnp.float32)
    y = y - jnp.mean(y, axis=0, keepdims=True)
    y = jnp.sqrt(jnp.mean(jnp.square(y), axis=0) + 1e-8)
    y = jnp.mean(y, axis=(1, 2, 3), keepdims=True)          # (n//g,1,1,1)
    y = jnp.broadcast_to(y[:, :, :, 0][None], (g, n // g, h, w))
    y = y.reshape(n, h, w, 1).astype(x.dtype)
    return jnp.concatenate([x, y], axis=-1)


def _lrelu(x: jax.Array) -> jax.Array:
    return jax.nn.leaky_relu(x, 0.2)


def stage_weights(lod: jax.Array, num_stages: int) -> jax.Array:
    """Triangle cross-fade weights per stage for a scalar lod.

    lod == num_stages-1 selects stage 0 (4x4); lod == 0 selects the full
    resolution; fractional lods linearly blend two adjacent stages — the
    fade-in the reference implements with per-level lerps inside the TF
    graph (pg_gans.py `G_paper`/`D_paper` growing structure).
    """
    stage_lods = jnp.arange(num_stages - 1, -1, -1, dtype=jnp.float32)
    return jnp.clip(1.0 - jnp.abs(lod - stage_lods), 0.0, 1.0)


# ---------------------------------------------------------------------------
# generator

def g_init(rng: jax.Array, cfg: PgganConfig) -> Params:
    keys = iter(jax.random.split(rng, 4 * cfg.num_stages + 4))
    in_dim = cfg.latent_size + cfg.label_size
    p: Params = {
        "latent_dense": eq_dense_init(next(keys), in_dim, cfg.nf(0) * 16),
        "stage0_conv": eq_conv_init(next(keys), 3, cfg.nf(0), cfg.nf(0)),
        "torgb": [eq_conv_init(next(keys), 1, cfg.nf(0), cfg.num_channels)],
        "blocks": [],
    }
    for s in range(1, cfg.num_stages):
        p["blocks"].append({
            "conv0": eq_conv_init(next(keys), 3, cfg.nf(s - 1), cfg.nf(s)),
            "conv1": eq_conv_init(next(keys), 3, cfg.nf(s), cfg.nf(s)),
        })
        p["torgb"].append(eq_conv_init(next(keys), 1, cfg.nf(s), cfg.num_channels))
    return p


def g_apply(p: Params, latents: jax.Array, labels: Optional[jax.Array],
            lod: jax.Array, cfg: PgganConfig,
            max_stage: Optional[int] = None) -> jax.Array:
    """latents (B, latent_size) -> images (B, R, R, C) in [-1, 1] range.

    ``max_stage`` (static) bounds the computed stages: during progressive
    growth the trainer passes the highest stage with nonzero fade weight so
    XLA never executes the dormant high-resolution convs.
    """
    top = cfg.num_stages - 1 if max_stage is None else max_stage
    dt = cfg.compute_dtype
    z = latents.astype(dt)
    if cfg.label_size:
        assert labels is not None
        z = jnp.concatenate([z, labels.astype(dt)], axis=-1)
    z = pixel_norm(z)
    x = eq_dense(p["latent_dense"], z, gain=math.sqrt(2.0) / 4.0)
    x = x.reshape(-1, 4, 4, cfg.nf(0))
    x = pixel_norm(_lrelu(x))
    x = pixel_norm(_lrelu(eq_conv(p["stage0_conv"], x)))

    w = stage_weights(lod, cfg.num_stages).astype(dt)
    img = eq_conv(p["torgb"][0], x, gain=1.0) * w[0]
    for s in range(1, top + 1):
        blk = p["blocks"][s - 1]
        x = upscale2d(x)
        x = pixel_norm(_lrelu(eq_conv(blk["conv0"], x)))
        x = pixel_norm(_lrelu(eq_conv(blk["conv1"], x)))
        img = upscale2d(img) + eq_conv(p["torgb"][s], x, gain=1.0) * w[s]
    # bring to final resolution regardless of how far we grew
    for _ in range(top + 1, cfg.num_stages):
        img = upscale2d(img)
    return img.astype(jnp.float32)


# ---------------------------------------------------------------------------
# discriminator

def d_init(rng: jax.Array, cfg: PgganConfig) -> Params:
    keys = iter(jax.random.split(rng, 4 * cfg.num_stages + 6))
    p: Params = {"fromrgb": [], "blocks": []}
    for s in range(cfg.num_stages):
        p["fromrgb"].append(eq_conv_init(next(keys), 1, cfg.num_channels, cfg.nf(s)))
    for s in range(cfg.num_stages - 1, 0, -1):
        p["blocks"].append({
            "conv0": eq_conv_init(next(keys), 3, cfg.nf(s), cfg.nf(s)),
            "conv1": eq_conv_init(next(keys), 3, cfg.nf(s), cfg.nf(s - 1)),
        })
    p["stage0_conv"] = eq_conv_init(next(keys), 3, cfg.nf(0) + 1, cfg.nf(0))
    p["stage0_dense"] = eq_dense_init(next(keys), cfg.nf(0) * 16, cfg.nf(0))
    p["head"] = eq_dense_init(next(keys), cfg.nf(0), 1 + cfg.label_size)
    return p


def d_apply(p: Params, images: jax.Array, lod: jax.Array, cfg: PgganConfig,
            max_stage: Optional[int] = None
            ) -> Tuple[jax.Array, Optional[jax.Array]]:
    """images (B, R, R, C) -> (critic scores (B,), label logits or None).

    Skip-style growing: the (suitably downscaled) image is injected through
    each stage's fromRGB head with the same fade weights the generator uses —
    equivalent in the limit to the reference's lerp-based `D_paper` growth,
    but with no data-dependent structure for XLA to re-trace.
    """
    top = cfg.num_stages - 1 if max_stage is None else max_stage
    dt = cfg.compute_dtype
    img = images.astype(dt)
    w = stage_weights(lod, cfg.num_stages).astype(dt)

    # image pyramid down to 4x4
    pyramid = [img]
    for _ in range(cfg.num_stages - 1):
        pyramid.append(downscale2d(pyramid[-1]))
    # pyramid[i] has resolution of stage (num_stages-1-i)

    x = None
    for s in range(top, 0, -1):
        inject = _lrelu(eq_conv(p["fromrgb"][s], pyramid[cfg.num_stages - 1 - s])) * w[s]
        x = inject if x is None else x + inject
        blk = p["blocks"][cfg.num_stages - 1 - s]
        x = _lrelu(eq_conv(blk["conv0"], x))
        x = _lrelu(eq_conv(blk["conv1"], x))
        x = downscale2d(x)
    inject = _lrelu(eq_conv(p["fromrgb"][0], pyramid[-1])) * w[0]
    x = inject if x is None else x + inject

    x = minibatch_stddev(x, cfg.mbstd_group_size)
    x = _lrelu(eq_conv(p["stage0_conv"], x))
    x = x.reshape(x.shape[0], -1)
    x = _lrelu(eq_dense(p["stage0_dense"], x))
    out = eq_dense(p["head"], x, gain=1.0).astype(jnp.float32)
    scores = out[:, 0]
    logits = out[:, 1:] if cfg.label_size else None
    return scores, logits


# ---------------------------------------------------------------------------
# losses (WGAN-GP + ACGAN — reference pg_gans.py:1276-1330 behavior)

def _acgan_term(logits: Optional[jax.Array], labels: Optional[jax.Array],
                cfg: PgganConfig) -> jax.Array:
    if not cfg.label_size:
        return jnp.zeros(())
    logp = jax.nn.log_softmax(logits, axis=-1)
    return -cfg.cond_weight * jnp.mean(jnp.sum(labels * logp, axis=-1))


def g_loss_fn(g_params: Params, d_params: Params, latents: jax.Array,
              labels: Optional[jax.Array], lod: jax.Array, cfg: PgganConfig,
              max_stage: Optional[int]) -> jax.Array:
    fakes = g_apply(g_params, latents, labels, lod, cfg, max_stage)
    scores, logits = d_apply(d_params, fakes, lod, cfg, max_stage)
    return -jnp.mean(scores) + _acgan_term(logits, labels, cfg)


def d_loss_fn(d_params: Params, g_params: Params, reals: jax.Array,
              latents: jax.Array, labels: Optional[jax.Array], lod: jax.Array,
              rng: jax.Array, cfg: PgganConfig,
              max_stage: Optional[int]) -> Tuple[jax.Array, Dict[str, jax.Array]]:
    fakes = g_apply(g_params, latents, labels, lod, cfg, max_stage)
    real_scores, real_logits = d_apply(d_params, reals, lod, cfg, max_stage)
    fake_scores, fake_logits = d_apply(d_params, fakes, lod, cfg, max_stage)
    wdist = jnp.mean(real_scores) - jnp.mean(fake_scores)
    loss = -wdist

    # gradient penalty on real/fake interpolates (second-order autodiff —
    # the reference assembles this by hand with tf.gradients, :1295-1310)
    eps = jax.random.uniform(rng, (reals.shape[0], 1, 1, 1), jnp.float32)
    mixed = reals + eps * (fakes - reals)

    def critic_sum(imgs):
        s, _ = d_apply(d_params, imgs, lod, cfg, max_stage)
        return jnp.sum(s)

    grads = jax.grad(critic_sum)(mixed)
    norms = jnp.sqrt(jnp.sum(jnp.square(grads.astype(jnp.float32)),
                             axis=(1, 2, 3)) + 1e-8)
    loss = loss + cfg.gp_lambda * jnp.mean(jnp.square(norms - 1.0))
    loss = loss + cfg.eps_drift * jnp.mean(jnp.square(real_scores))
    loss = loss + _acgan_term(real_logits, labels, cfg)
    loss = loss + _acgan_term(fake_logits, labels, cfg)
    return loss, {"wdist": wdist, "gp_norm": jnp.mean(norms)}


# ---------------------------------------------------------------------------
# schedule (reference TrainingSchedule, pg_gans.py:1227-1274 semantics)

@dataclass(frozen=True)
class Schedule:
    lod: float
    resolution: int
    minibatch: int
    max_stage: int
    G_lrate: float
    D_lrate: float


def training_schedule(cur_nimg: int, cfg: PgganConfig,
                      minibatch_base: int = 16,
                      G_lrate: float = 1e-3, D_lrate: float = 1e-3,
                      lod_initial_resolution: int = 4,
                      lod_training_kimg: float = 600.0,
                      lod_transition_kimg: float = 600.0,
                      minibatch_dict: Optional[Dict[int, int]] = None,
                      ) -> Schedule:
    """Map training progress (images shown) to lod / minibatch / lrates.

    Phases of ``training+transition`` kimg per resolution doubling: hold lod
    constant for ``lod_training_kimg``, then fade it down linearly over
    ``lod_transition_kimg``.
    """
    kimg = cur_nimg / 1000.0
    max_lod = cfg.num_stages - 1
    lod = max_lod - (math.log2(lod_initial_resolution) - 2.0)
    phase_dur = lod_training_kimg + lod_transition_kimg
    phase_idx = math.floor(kimg / phase_dur) if phase_dur > 0 else 0
    phase_kimg = kimg - phase_idx * phase_dur
    lod -= phase_idx
    if lod_transition_kimg > 0:
        lod -= max(phase_kimg - lod_training_kimg, 0.0) / lod_transition_kimg
    lod = float(np.clip(lod, 0.0, max_lod))
    cur_stage_pos = max_lod - lod
    max_stage = min(cfg.num_stages - 1, int(math.ceil(cur_stage_pos - 1e-8)))
    resolution = 4 * 2 ** max_stage
    minibatch = (minibatch_dict or {}).get(resolution, minibatch_base)
    return Schedule(lod=lod, resolution=resolution, minibatch=minibatch,
                    max_stage=max_stage, G_lrate=G_lrate, D_lrate=D_lrate)


# ---------------------------------------------------------------------------
# trainer

class PgganTrainer:
    """Owns G/D/Gs params, per-stage-bucket jitted steps, and the growth loop.

    Data parallelism: batch args carry a NamedSharding over the mesh's
    ``data`` axis; params are replicated. XLA turns the batched gradient
    into an ICI all-reduce — the GSPMD replacement for the reference's
    explicit per-GPU graph clones + NCCL all_sum (pg_gans.py:1165-1170).
    """

    def __init__(self, cfg: PgganConfig, mesh: Optional[jax.sharding.Mesh] = None,
                 g_smoothing: float = 0.99, seed: int = 0):
        self.cfg = cfg
        self.mesh = mesh
        self.g_smoothing = g_smoothing
        kg, kd = jax.random.split(jax.random.PRNGKey(seed))
        self.g_params = g_init(kg, cfg)
        self.d_params = d_init(kd, cfg)
        self.gs_params = jax.tree.map(jnp.copy, self.g_params)
        self._opt: Dict[str, Any] = {}
        self._opt_state: Dict[str, Any] = {}
        self._steps: Dict[Tuple[int, int], Tuple[Callable, Callable]] = {}

        def ema(gs, g):
            b = self.g_smoothing
            return jax.tree.map(lambda a, c: a * b + c * (1.0 - b), gs, g)

        self._ema = jax.jit(ema)
        self._generate = jax.jit(g_apply, static_argnums=(4, 5))
        # the lod training last ran at — generate() samples here by default,
        # so a partially-grown model renders at its trained resolution
        # (the reference's Network keeps lod as a graph variable with the
        # same effect, pg_gans.py:301-303)
        self.last_lod: float = 0.0

    def _data_sharding(self):
        if self.mesh is None:
            return None
        return jax.sharding.NamedSharding(
            self.mesh, jax.sharding.PartitionSpec("data"))

    def init_optimizers(self, g_lr: float, d_lr: float) -> None:
        # Adam(0, 0.99) as the reference configures (pg_gans.py:297-299)
        self._opt["g"] = optax.adam(g_lr, b1=0.0, b2=0.99, eps=1e-8)
        self._opt["d"] = optax.adam(d_lr, b1=0.0, b2=0.99, eps=1e-8)
        self._steps.clear()  # jitted steps close over the optimizers
        self.reset_optimizer_state()

    def reset_optimizer_state(self) -> None:
        """Reference resets Adam moments at each lod change (:336-339)."""
        self._opt_state["g"] = self._opt["g"].init(self.g_params)
        self._opt_state["d"] = self._opt["d"].init(self.d_params)

    def _get_steps(self, max_stage: int, minibatch: int):
        key = (max_stage, minibatch)
        if key in self._steps:
            return self._steps[key]
        cfg = self.cfg

        def d_step(d_params, g_params, opt_state, reals, labels, lod, rng):
            zkey, gpkey = jax.random.split(rng)
            latents = jax.random.normal(zkey, (minibatch, cfg.latent_size))
            (loss, aux), grads = jax.value_and_grad(d_loss_fn, has_aux=True)(
                d_params, g_params, reals, latents, labels, lod, gpkey,
                cfg, max_stage)
            updates, opt_state = self._opt["d"].update(grads, opt_state, d_params)
            return optax.apply_updates(d_params, updates), opt_state, loss, aux

        def g_step(g_params, d_params, opt_state, labels, lod, rng):
            latents = jax.random.normal(rng, (minibatch, cfg.latent_size))
            loss, grads = jax.value_and_grad(g_loss_fn)(
                g_params, d_params, latents, labels, lod, cfg, max_stage)
            updates, opt_state = self._opt["g"].update(grads, opt_state, g_params)
            return optax.apply_updates(g_params, updates), opt_state, loss

        jd = jax.jit(d_step, donate_argnums=(0, 2))
        jg = jax.jit(g_step, donate_argnums=(0, 2))
        self._steps[key] = (jd, jg)
        return jd, jg

    def train(self, images: np.ndarray, labels: Optional[np.ndarray] = None,
              total_kimg: float = 2.0, D_repeats: int = 1,
              minibatch_repeats: int = 4, minibatch_base: int = 16,
              G_lrate: float = 1e-3, D_lrate: float = 1e-3,
              lod_initial_resolution: int = 4,
              reset_opt_for_new_lod: bool = True,
              lod_training_kimg: float = 600.0,
              lod_transition_kimg: float = 600.0,
              log: Optional[Callable[..., None]] = None,
              seed: int = 0) -> Dict[str, float]:
        """The growth loop (reference pg_gans.py:328-343 behavior).

        ``images`` are NHWC float32 in [-1, 1] at ``cfg.resolution``; when
        the schedule renders below full resolution the reals are average-
        pooled down and nearest-upscaled back (reference `_process_reals`
        blending, pg_gans.py:345-378) — done here by D's own image pyramid,
        so reals are fed at full resolution always.
        """
        cfg = self.cfg
        self.init_optimizers(G_lrate, D_lrate)
        host_rng = np.random.default_rng(seed)
        key = jax.random.PRNGKey(seed + 1)
        sharding = self._data_sharding()
        n_shards = 1 if self.mesh is None else self.mesh.shape["data"]

        cur_nimg, prev_lod, metrics = 0, -1.0, {}
        while cur_nimg < total_kimg * 1000:
            sched = training_schedule(
                cur_nimg, cfg, minibatch_base=minibatch_base,
                G_lrate=G_lrate, D_lrate=D_lrate,
                lod_initial_resolution=lod_initial_resolution,
                lod_training_kimg=lod_training_kimg,
                lod_transition_kimg=lod_transition_kimg)
            mb = max(n_shards, (sched.minibatch // n_shards) * n_shards)
            if reset_opt_for_new_lod and prev_lod >= 0 and (
                    math.floor(sched.lod) != math.floor(prev_lod)
                    or math.ceil(sched.lod) != math.ceil(prev_lod)):
                self.reset_optimizer_state()
            prev_lod = sched.lod
            d_step, g_step = self._get_steps(sched.max_stage, mb)
            lod = jnp.float32(sched.lod)

            for _ in range(minibatch_repeats):
                for _ in range(D_repeats):
                    idx = host_rng.integers(0, images.shape[0], size=mb)
                    reals = jnp.asarray(images[idx])
                    lbls = (jnp.asarray(labels[idx]) if labels is not None
                            and cfg.label_size else None)
                    if sharding is not None:
                        reals = jax.device_put(reals, sharding)
                    key, sub = jax.random.split(key)
                    self.d_params, self._opt_state["d"], d_loss, aux = d_step(
                        self.d_params, self.g_params, self._opt_state["d"],
                        reals, lbls, lod, sub)
                    cur_nimg += mb
                key, sub = jax.random.split(key)
                lbls = None
                if labels is not None and cfg.label_size:
                    idx = host_rng.integers(0, labels.shape[0], size=mb)
                    lbls = jnp.asarray(labels[idx])
                self.g_params, self._opt_state["g"], g_loss = g_step(
                    self.g_params, self.d_params, self._opt_state["g"],
                    lbls, lod, sub)
                # EMA once per G update (the reference ties its Gs update to
                # the D step instead, pg_gans.py:335 — updating after the G
                # step is the original ProGAN semantics and ensures the last
                # G update is always folded into Gs)
                self.gs_params = self._ema(self.gs_params, self.g_params)

            self.last_lod = sched.lod
            metrics = {"d_loss": float(d_loss), "g_loss": float(g_loss),
                       "wdist": float(aux["wdist"]), "lod": sched.lod,
                       "kimg": cur_nimg / 1000.0}
            if log is not None:
                log("pggan tick", **metrics)
        return metrics

    def generate(self, n: int, labels: Optional[np.ndarray] = None,
                 seed: int = 0, use_ema: bool = True,
                 lod: Optional[float] = None) -> np.ndarray:
        """Sample n images in [-1, 1] from Gs (the EMA generator) at the
        lod training last reached (or an explicit override)."""
        params = self.gs_params if use_ema else self.g_params
        key = jax.random.PRNGKey(seed)
        latents = jax.random.normal(key, (n, self.cfg.latent_size))
        lbls = jnp.asarray(labels) if labels is not None else None
        lod_val = self.last_lod if lod is None else lod
        imgs = self._generate(params, latents, lbls, jnp.float32(lod_val),
                              self.cfg, None)
        return np.asarray(imgs)


def partition_specs(cfg: PgganConfig) -> Any:
    """GAN training is pure data parallelism: params fully replicated."""
    P = jax.sharding.PartitionSpec
    return {"g": P(), "d": P()}
