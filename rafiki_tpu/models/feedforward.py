"""Small dense feed-forward classifier — the quickstart workhorse.

Parity target: the reference's TfFeedForward example (reference
examples/models/image_classification/TfFeedForward.py:14-164) — a flattened-
image MLP with knob-tunable depth/width/lr/epochs — re-expressed as pure
init/apply functions consumed by either trainer.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Any, Dict, Tuple

import jax
import jax.numpy as jnp

from rafiki_tpu.models import core

Params = Dict[str, Any]


@dataclass(frozen=True)
class FeedForwardConfig:
    in_dim: int = 784
    hidden_layers: int = 1
    hidden_units: int = 128
    num_classes: int = 10


def init(rng: jax.Array, cfg: FeedForwardConfig) -> Params:
    keys = jax.random.split(rng, cfg.hidden_layers + 1)
    layers = []
    d = cfg.in_dim
    for i in range(cfg.hidden_layers):
        layers.append(core.dense_init(keys[i], d, cfg.hidden_units))
        d = cfg.hidden_units
    return {"layers": layers,
            "head": core.dense_init(keys[-1], d, cfg.num_classes)}


def apply(params: Params, x: jax.Array, cfg: FeedForwardConfig) -> jax.Array:
    """x: (B, ...) flattened to (B, in_dim) -> logits (B, classes)."""
    x = core.cast_for_compute(x.reshape(x.shape[0], -1))
    for layer in params["layers"]:
        x = jax.nn.relu(core.dense(layer, x))
    return core.dense(params["head"], x).astype(jnp.float32)
