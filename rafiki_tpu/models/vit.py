"""Vision Transformer — the flagship model of the TPU build.

ViT-B/16 is one of the driver's north-star configs (BASELINE.json: "ImageNet
ViT-B/16 multi-worker pjit train job + predictor batched serving"). The
design is MXU-shaped end to end: patchify is a single strided conv, the
encoder is the scan-stacked transformer (models/transformer.py), pooling is
GAP (no ragged cls-token gather, and the sequence axis stays uniformly
shardable for SP), and the whole forward runs in bfloat16.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Dict, Optional

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from rafiki_tpu.models import core
from rafiki_tpu.models.transformer import (
    TransformerConfig,
    block_partition_specs,
    stack_apply,
    stack_init,
)

Params = Dict[str, Any]


@dataclass(frozen=True)
class ViTConfig:
    image_size: int = 224
    patch_size: int = 16
    channels: int = 3
    num_classes: int = 1000
    encoder: TransformerConfig = field(default_factory=TransformerConfig)

    @property
    def seq_len(self) -> int:
        return (self.image_size // self.patch_size) ** 2


def vit_b16(num_classes: int = 1000, image_size: int = 224) -> ViTConfig:
    return ViTConfig(image_size=image_size, num_classes=num_classes,
                     encoder=TransformerConfig(dim=768, depth=12, heads=12))


def tiny(num_classes: int = 10, image_size: int = 32, patch_size: int = 4,
         dim: int = 64, depth: int = 2, heads: int = 4) -> ViTConfig:
    """A test-scale config (compiles in seconds; used by unit tests and the
    multichip dry run)."""
    return ViTConfig(image_size=image_size, patch_size=patch_size,
                     num_classes=num_classes,
                     encoder=TransformerConfig(dim=dim, depth=depth, heads=heads))


def init(rng: jax.Array, cfg: ViTConfig) -> Params:
    k_patch, k_pos, k_blocks, k_head = jax.random.split(rng, 4)
    p = cfg.patch_size
    return {
        "patch": core.conv2d_init(k_patch, p, p, cfg.channels, cfg.encoder.dim),
        "pos": core.normal_init(k_pos, (1, cfg.seq_len, cfg.encoder.dim)),
        "blocks": stack_init(k_blocks, cfg.encoder),
        "ln_f": core.layernorm_init(cfg.encoder.dim),
        "head": core.dense_init(k_head, cfg.encoder.dim, cfg.num_classes),
    }


def apply(params: Params, images: jax.Array, cfg: ViTConfig,
          rng: Optional[jax.Array] = None,
          deterministic: bool = True) -> jax.Array:
    """images: (B, H, W, C) float -> logits (B, num_classes)."""
    x = core.cast_for_compute(images)
    x = core.conv2d(params["patch"], x, stride=cfg.patch_size, padding="VALID")
    b = x.shape[0]
    x = x.reshape(b, cfg.seq_len, cfg.encoder.dim)
    x = x + params["pos"].astype(x.dtype)
    x, _ = stack_apply(params["blocks"], x, cfg.encoder, rng, deterministic)
    x = core.layernorm(params["ln_f"], x)
    x = jnp.mean(x, axis=1)  # GAP — SP-friendly (uniform over sequence)
    return core.dense(params["head"], x).astype(jnp.float32)


def partition_specs(cfg: ViTConfig) -> Params:
    """Param PartitionSpecs: transformer blocks TP-sharded (and pipe-sharded
    on their stacked depth axis); everything else replicated."""
    return {
        "patch": {"kernel": P(None, None, None, None), "bias": P(None)},
        "pos": P(None, None, None),
        "blocks": block_partition_specs(cfg.encoder, stacked=True),
        "ln_f": {"scale": P(None), "bias": P(None)},
        "head": {"kernel": P(None, None), "bias": P(None)},
    }


def batch_spec() -> Any:
    """Activations: batch over data, sequence over seq (SP), features full."""
    return P("data", None, None, None)
