"""First-party model zoo: pure-pytree JAX models designed for the MXU.

Every model here is a pair of pure functions — ``init(rng, cfg) -> params``
and ``apply(params, inputs, ...) -> outputs`` — over plain dict pytrees, plus
a ``partition_specs(cfg)`` pytree of :class:`jax.sharding.PartitionSpec` so
the parallel layer (rafiki_tpu/parallel) can shard them over any mesh without
model-specific code. No framework classes, no tracing magic: everything is
jit-/scan-/shard_map-compatible by construction.
"""

from rafiki_tpu.models import core  # noqa: F401
