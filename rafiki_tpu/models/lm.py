"""Decoder-only transformer LM — the long-context / MoE vehicle.

Causal transformer over token ids with optional expert-parallel MoE FFNs
(TransformerConfig.moe_experts) and tied-embedding output head. Exercises
every mesh axis: data (batch), model (TP heads/MLP), seq (SP activations /
ring attention), expert (MoE), pipe (stacked depth). The reference system
has no language model at all; this backs the BASELINE.json BERT/ENAS config
and the long-context requirement.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Dict, Optional, Tuple

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from rafiki_tpu.models import core
from rafiki_tpu.models.transformer import (
    TransformerConfig,
    block_partition_specs,
    stack_apply,
    stack_init,
)

Params = Dict[str, Any]


@dataclass(frozen=True)
class LMConfig:
    vocab: int = 32000
    max_len: int = 2048
    encoder: TransformerConfig = field(
        default_factory=lambda: TransformerConfig(causal=True))


def tiny(vocab: int = 256, max_len: int = 128, dim: int = 64, depth: int = 2,
         heads: int = 4, moe_experts: int = 0, **encoder_kw) -> LMConfig:
    """``encoder_kw`` passes through to TransformerConfig (seq_parallel,
    pipeline, n_microbatches, ...)."""
    return LMConfig(vocab=vocab, max_len=max_len,
                    encoder=TransformerConfig(dim=dim, depth=depth,
                                              heads=heads, causal=True,
                                              moe_experts=moe_experts,
                                              **encoder_kw))


def init(rng: jax.Array, cfg: LMConfig) -> Params:
    k_emb, k_pos, k_blocks = jax.random.split(rng, 3)
    return {
        "embed": core.embedding_init(k_emb, cfg.vocab, cfg.encoder.dim),
        "pos": core.normal_init(k_pos, (1, cfg.max_len, cfg.encoder.dim)),
        "blocks": stack_init(k_blocks, cfg.encoder),
        "ln_f": core.layernorm_init(cfg.encoder.dim),
    }


def apply(params: Params, ids: jax.Array, cfg: LMConfig,
          rng: Optional[jax.Array] = None, deterministic: bool = True
          ) -> Tuple[jax.Array, jax.Array]:
    """ids: (B, S) int32 -> (logits (B, S, V) f32, moe aux loss)."""
    s = ids.shape[1]
    x = core.embedding(params["embed"], ids)
    x = x + params["pos"][:, :s, :].astype(x.dtype)
    x, aux = stack_apply(params["blocks"], x, cfg.encoder, rng, deterministic)
    x = core.layernorm(params["ln_f"], x)
    # tied output head: logits = x @ E^T
    logits = jnp.einsum("bsd,vd->bsv", x,
                        params["embed"]["table"].astype(x.dtype))
    return logits.astype(jnp.float32), aux


def loss_fn(params: Params, batch: Tuple[jax.Array, jax.Array],
            rng: jax.Array, cfg: LMConfig,
            aux_weight: float = 1e-2) -> Tuple[jax.Array, Dict[str, jax.Array]]:
    """Next-token cross-entropy; batch = (ids, mask)."""
    import optax

    ids, mask = batch
    logits, aux = apply(params, ids, cfg, rng, deterministic=False)
    targets = ids[:, 1:]
    lm_mask = mask[:, 1:].astype(jnp.float32)
    ce = optax.softmax_cross_entropy_with_integer_labels(
        logits[:, :-1], targets)
    loss = jnp.sum(ce * lm_mask) / jnp.maximum(jnp.sum(lm_mask), 1.0)
    total = loss + aux_weight * aux
    return total, {"ce": loss, "moe_aux": aux}


def partition_specs(cfg: LMConfig) -> Params:
    return {
        "embed": {"table": P(None, "model")},
        "pos": P(None, None, None),
        "blocks": block_partition_specs(cfg.encoder, stacked=True),
        "ln_f": {"scale": P(None), "bias": P(None)},
    }


def batch_spec() -> Any:
    return (P("data", "seq"), P("data", "seq"))
