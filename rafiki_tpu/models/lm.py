"""Decoder-only transformer LM — the long-context / MoE vehicle.

Causal transformer over token ids with optional expert-parallel MoE FFNs
(TransformerConfig.moe_experts) and tied-embedding output head. Exercises
every mesh axis: data (batch), model (TP heads/MLP), seq (SP activations /
ring attention), expert (MoE), pipe (stacked depth). The reference system
has no language model at all; this backs the BASELINE.json BERT/ENAS config
and the long-context requirement.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Dict, Optional, Tuple

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from rafiki_tpu.models import core
from rafiki_tpu.models.transformer import (
    TransformerConfig,
    block_partition_specs,
    stack_apply,
    stack_init,
)

Params = Dict[str, Any]


@dataclass(frozen=True)
class LMConfig:
    vocab: int = 32000
    max_len: int = 2048
    encoder: TransformerConfig = field(
        default_factory=lambda: TransformerConfig(causal=True))


def tiny(vocab: int = 256, max_len: int = 128, dim: int = 64, depth: int = 2,
         heads: int = 4, moe_experts: int = 0, **encoder_kw) -> LMConfig:
    """``encoder_kw`` passes through to TransformerConfig (seq_parallel,
    pipeline, n_microbatches, ...)."""
    return LMConfig(vocab=vocab, max_len=max_len,
                    encoder=TransformerConfig(dim=dim, depth=depth,
                                              heads=heads, causal=True,
                                              moe_experts=moe_experts,
                                              **encoder_kw))


def init(rng: jax.Array, cfg: LMConfig) -> Params:
    k_emb, k_pos, k_blocks = jax.random.split(rng, 3)
    return {
        "embed": core.embedding_init(k_emb, cfg.vocab, cfg.encoder.dim),
        "pos": core.normal_init(k_pos, (1, cfg.max_len, cfg.encoder.dim)),
        "blocks": stack_init(k_blocks, cfg.encoder),
        "ln_f": core.layernorm_init(cfg.encoder.dim),
    }


def apply(params: Params, ids: jax.Array, cfg: LMConfig,
          rng: Optional[jax.Array] = None, deterministic: bool = True
          ) -> Tuple[jax.Array, jax.Array]:
    """ids: (B, S) int32 -> (logits (B, S, V) f32, moe aux loss)."""
    s = ids.shape[1]
    x = core.embedding(params["embed"], ids)
    x = x + params["pos"][:, :s, :].astype(x.dtype)
    x, aux = stack_apply(params["blocks"], x, cfg.encoder, rng, deterministic)
    x = core.layernorm(params["ln_f"], x)
    # tied output head: logits = x @ E^T
    logits = jnp.einsum("bsd,vd->bsv", x,
                        params["embed"]["table"].astype(x.dtype))
    return logits.astype(jnp.float32), aux


def loss_fn(params: Params, batch: Tuple[jax.Array, jax.Array],
            rng: jax.Array, cfg: LMConfig,
            aux_weight: float = 1e-2) -> Tuple[jax.Array, Dict[str, jax.Array]]:
    """Next-token cross-entropy; batch = (ids, mask)."""
    import optax

    ids, mask = batch
    logits, aux = apply(params, ids, cfg, rng, deterministic=False)
    targets = ids[:, 1:]
    lm_mask = mask[:, 1:].astype(jnp.float32)
    ce = optax.softmax_cross_entropy_with_integer_labels(
        logits[:, :-1], targets)
    loss = jnp.sum(ce * lm_mask) / jnp.maximum(jnp.sum(lm_mask), 1.0)
    total = loss + aux_weight * aux
    return total, {"ce": loss, "moe_aux": aux}


# -- autoregressive decode path (generative serving) -------------------------
#
# The serving subsystem (worker/generation.py) drives these three functions:
# ``init_kv_cache`` preallocates a fixed-shape per-layer K/V ring for a fixed
# number of sequence SLOTS, ``prefill`` ingests one slot's prompt (same math
# as ``apply`` — causal full-sequence attention — while also writing the
# prompt's K/V into the slot), and ``decode_step`` advances EVERY slot by one
# token against the cache. All shapes are fixed at cache-allocation time, so
# one jitted decode program serves the whole lifetime of the batch: sequences
# join (prefill) and leave (slot reuse) without recompiling, which is what
# makes token-level continuous batching cheap.
#
# Both forwards share one implementation (``_cached_forward``): prefill is
# the T=P case with positions 0..P-1, decode the T=1 case at each slot's
# current position. Dense blocks only — MoE routing differs per token batch
# and is refused at cache init.

Cache = Dict[str, jax.Array]


def init_kv_cache(cfg: LMConfig, max_slots: int,
                  max_len: Optional[int] = None,
                  dtype=jnp.float32) -> Cache:
    """Preallocate the decode cache: per-layer K/V of shape
    ``(depth, max_slots, max_len, heads, head_dim)``. ``max_len`` defaults
    to ``cfg.max_len`` (prompt + generated tokens must fit)."""
    if cfg.encoder.moe_experts > 0:
        raise ValueError(
            "KV-cached decode supports dense blocks only (moe_experts=0): "
            "MoE top-k routing is per-token and the fixed-shape decode "
            "program cannot carry its dispatch state in the cache")
    enc = cfg.encoder
    max_len = int(max_len or cfg.max_len)
    shape = (enc.depth, int(max_slots), max_len, enc.heads,
             enc.dim // enc.heads)
    return {"k": jnp.zeros(shape, dtype), "v": jnp.zeros(shape, dtype)}


def cache_max_len(cache: Cache) -> int:
    return int(cache["k"].shape[2])


def cache_max_slots(cache: Cache) -> int:
    return int(cache["k"].shape[1])


def _cached_forward(params: Params, ck: jax.Array, cv: jax.Array,
                    ids: jax.Array, positions: jax.Array, cfg: LMConfig
                    ) -> Tuple[jax.Array, jax.Array, jax.Array]:
    """Shared prefill/decode forward over per-slot caches.

    ``ids``/``positions``: (B, T) int32 — token ids and the cache indices
    they occupy. ``ck``/``cv``: (depth, B, L, H, Dh) — the cache rows of
    the B slots being advanced. New K/V are written at ``positions`` and
    attention reads the cache up to each query's own position (causal by
    construction). Returns (logits (B, T, V) f32, new_ck, new_cv).
    Same math as :func:`apply` for dense blocks (reference attention,
    f32 softmax statistics), so a prefilled-then-decoded sequence tracks
    the full-sequence forward."""
    enc = cfg.encoder
    b, t = ids.shape
    length = ck.shape[2]
    compute_dtype = ck.dtype
    x = core.embedding(params["embed"], ids, dtype=compute_dtype)
    pos_table = params["pos"][0].astype(compute_dtype)  # (max_len, D)
    x = x + jnp.take(pos_table, positions, axis=0)      # (B, T, D)
    batch_ix = jnp.arange(b)[:, None]                   # (B, 1)
    # (B, T, L): query token at positions[b, i] attends cache slots <= it
    mask = jnp.arange(length)[None, None, :] <= positions[:, :, None]
    scale = 1.0 / jnp.sqrt(jnp.asarray(enc.dim // enc.heads, jnp.float32))

    def body(x, layer):
        p, lk, lv = layer  # block params, (B, L, H, Dh) cache planes
        h = core.layernorm(p["ln1"], x)
        q = jnp.einsum("btd,dhk->bthk", h, p["attn"]["wq"].astype(x.dtype))
        k = jnp.einsum("btd,dhk->bthk", h, p["attn"]["wk"].astype(x.dtype))
        v = jnp.einsum("btd,dhk->bthk", h, p["attn"]["wv"].astype(x.dtype))
        lk = lk.at[batch_ix, positions].set(k.astype(lk.dtype))
        lv = lv.at[batch_ix, positions].set(v.astype(lv.dtype))
        s = jnp.einsum("bthk,blhk->bthl", q, lk.astype(q.dtype),
                       preferred_element_type=jnp.float32) * scale
        s = jnp.where(mask[:, :, None, :], s, -1e30)  # broadcast over H
        a = jax.nn.softmax(s, axis=-1).astype(q.dtype)
        o = jnp.einsum("bthl,blhk->bthk", a, lv.astype(q.dtype))
        attn_out = jnp.einsum(
            "bthk,hkd->btd", o, p["attn"]["wo"].astype(x.dtype))
        x = x + attn_out + p["attn"]["bo"].astype(x.dtype)
        h = core.layernorm(p["ln2"], x)
        h = core.dense(p["mlp"]["w1"], h)
        h = jax.nn.gelu(h)
        h = core.dense(p["mlp"]["w2"], h)
        return x + h, (lk, lv)

    x, (new_ck, new_cv) = jax.lax.scan(body, x, (params["blocks"], ck, cv))
    x = core.layernorm(params["ln_f"], x)
    logits = jnp.einsum("btd,vd->btv", x,
                        params["embed"]["table"].astype(x.dtype))
    return logits.astype(jnp.float32), new_ck, new_cv


def prefill(params: Params, cache: Cache, slot: jax.Array, ids: jax.Array,
            length: jax.Array, cfg: LMConfig) -> Tuple[jax.Array, Cache]:
    """Ingest one slot's prompt: write its K/V into ``cache[:, slot]`` and
    return the next-token logits at the prompt's last REAL position.

    ``ids``: (T,) int32, right-padded to any fixed bucket length so one
    compiled prefill serves every prompt of that bucket; ``length`` is the
    true prompt length (pad K/V beyond it are written but sit above the
    decode frontier, and each decode step overwrites the next index before
    attention can ever reach it). Returns (logits (V,), cache)."""
    ids = jnp.asarray(ids, jnp.int32)[None]                    # (1, T)
    positions = jnp.arange(ids.shape[1], dtype=jnp.int32)[None]
    ck = cache["k"][:, slot][:, None]                          # (D, 1, L, H, Dh)
    cv = cache["v"][:, slot][:, None]
    logits, ck, cv = _cached_forward(params, ck, cv, ids, positions, cfg)
    cache = {"k": cache["k"].at[:, slot].set(ck[:, 0]),
             "v": cache["v"].at[:, slot].set(cv[:, 0])}
    last = jnp.asarray(length, jnp.int32) - 1
    return logits[0, last], cache


def decode_step(params: Params, cache: Cache, ids: jax.Array,
                positions: jax.Array, cfg: LMConfig
                ) -> Tuple[jax.Array, Cache]:
    """Advance every slot one token: ``ids``/``positions`` are (S,) int32
    (the last emitted token per slot and the cache index it lands at).
    Returns (logits (S, V) f32, cache). Fixed shapes — one jitted program
    serves the batch for its whole lifetime; idle slots are advanced too
    (their outputs are ignored by the scheduler), which wastes flops but
    never recompiles."""
    ids = jnp.asarray(ids, jnp.int32)[:, None]                 # (S, 1)
    positions = jnp.asarray(positions, jnp.int32)[:, None]
    logits, ck, cv = _cached_forward(
        params, cache["k"], cache["v"], ids, positions, cfg)
    return logits[:, 0], {"k": ck, "v": cv}


# -- paged KV cache (block-granular decode memory) ---------------------------
#
# The contiguous ring above preallocates ``max_slots x max_len`` K/V rows
# whatever the actual sequence lengths are — HBM cost is worst-case, which
# caps co-resident streams. The paged layout (PagedAttention, vLLM) keeps
# one flat POOL of fixed-size blocks (``block_tokens`` K/V rows each) plus a
# per-slot BLOCK TABLE mapping logical positions to physical blocks, so a
# slot only holds blocks for tokens it has actually written — and blocks
# whose contents are a shared prompt prefix can appear in many tables at
# once (the worker-side allocator, worker/kv_paging.py, owns refcounts and
# copy-on-write; this layer is pure array math).
#
# Shapes stay fixed: every forward gathers the slot's logical view
# ``(depth, B, table_blocks*block_tokens, H, Dh)`` from the pool through
# the table, runs the SAME ``_cached_forward`` as the ring path (so paged
# outputs are bit-identical given the same logical contents), then scatters
# ONLY the newly-written rows back. Sentinel table entries (>= pool size)
# gather clipped garbage that the causal mask keeps out of every real
# query, and their writes are dropped (`mode="drop"`), so idle slots and
# bucket padding never touch a live block.

def init_paged_kv_cache(cfg: LMConfig, pool_blocks: int, block_tokens: int,
                        dtype=jnp.float32) -> Cache:
    """Preallocate the paged decode pool: per-layer K/V of shape
    ``(depth, pool_blocks, block_tokens, heads, head_dim)``. Same MoE
    refusal as the ring cache — the fixed-shape decode program cannot
    carry per-token dispatch state."""
    if cfg.encoder.moe_experts > 0:
        raise ValueError(
            "KV-cached decode supports dense blocks only (moe_experts=0): "
            "MoE top-k routing is per-token and the fixed-shape decode "
            "program cannot carry its dispatch state in the cache")
    enc = cfg.encoder
    shape = (enc.depth, int(pool_blocks), int(block_tokens), enc.heads,
             enc.dim // enc.heads)
    return {"k": jnp.zeros(shape, dtype), "v": jnp.zeros(shape, dtype)}


def paged_pool_blocks(cache: Cache) -> int:
    return int(cache["k"].shape[1])


def paged_block_tokens(cache: Cache) -> int:
    return int(cache["k"].shape[2])


def paged_pool_bytes(cache: Cache) -> int:
    """Persistent HBM the pool holds (both K and V planes)."""
    return int(cache["k"].nbytes + cache["v"].nbytes)


def _paged_view(plane: jax.Array, block_tables: jax.Array) -> jax.Array:
    """Gather logical per-slot views from the pool: ``plane`` is
    (depth, NBpool, BT, H, Dh), ``block_tables`` (B, NB) int32 ->
    (depth, B, NB*BT, H, Dh). Out-of-range (sentinel) entries clip to the
    last pool block — finite garbage the mask excludes."""
    depth = plane.shape[0]
    b, nb = block_tables.shape
    bt, h, dh = plane.shape[2], plane.shape[3], plane.shape[4]
    flat = jnp.take(plane, block_tables.reshape(-1), axis=1, mode="clip")
    return flat.reshape(depth, b, nb, bt, h, dh).reshape(
        depth, b, nb * bt, h, dh)


def _scatter_rows(plane: jax.Array, new_view: jax.Array,
                  block_tables: jax.Array, positions: jax.Array
                  ) -> jax.Array:
    """Write the view rows at ``positions`` back into the pool.

    ``new_view``: (depth, B, L, H, Dh) updated logical views;
    ``positions``: (B, T) logical indices that were written this call.
    Rows mapping through a sentinel table entry (or past the table) are
    dropped — never clamped onto a live block."""
    nbpool = plane.shape[1]
    bt = plane.shape[2]
    b, t = positions.shape
    nb = block_tables.shape[1]
    limit = nb * bt
    blk_ix = jnp.clip(positions // bt, 0, nb - 1)               # (B, T)
    phys = jnp.take_along_axis(block_tables, blk_ix, axis=1)    # (B, T)
    phys = jnp.where(positions < limit, phys, nbpool)           # drop pads
    off = positions % bt
    # rows being written: (depth, B, T, H, Dh)
    vals = jnp.take_along_axis(
        new_view, positions[None, :, :, None, None], axis=2)
    return plane.at[:, phys, off].set(vals, mode="drop")


def paged_prefill(params: Params, cache: Cache, block_table: jax.Array,
                  ids: jax.Array, start: jax.Array, length: jax.Array,
                  cfg: LMConfig) -> Tuple[jax.Array, Cache]:
    """Ingest (a chunk of) one slot's prompt at logical positions
    ``start .. start+T-1``. ``block_table``: (NB,) int32 physical blocks
    covering the slot's logical space (sentinel entries for unallocated
    tails); ``ids``: (T,) suffix tokens right-padded to a bucket;
    ``length`` the true token count of this chunk. Returns
    (logits (V,) at the chunk's last REAL position, cache) — for
    intermediate chunks of a chunked prefill the caller ignores the
    logits; the final chunk's logits yield the first generated token."""
    ids = jnp.asarray(ids, jnp.int32)[None]                      # (1, T)
    t = ids.shape[1]
    start = jnp.asarray(start, jnp.int32)
    positions = (start + jnp.arange(t, dtype=jnp.int32))[None]   # (1, T)
    bt2 = jnp.asarray(block_table, jnp.int32)[None]              # (1, NB)
    vk = _paged_view(cache["k"], bt2)
    vv = _paged_view(cache["v"], bt2)
    logits, ck, cv = _cached_forward(params, vk, vv, ids, positions, cfg)
    cache = {"k": _scatter_rows(cache["k"], ck, bt2, positions),
             "v": _scatter_rows(cache["v"], cv, bt2, positions)}
    last = jnp.asarray(length, jnp.int32) - 1
    return logits[0, last], cache


def paged_decode_step(params: Params, cache: Cache, ids: jax.Array,
                      positions: jax.Array, block_tables: jax.Array,
                      cfg: LMConfig) -> Tuple[jax.Array, Cache]:
    """Advance every slot one token against the pool: ``ids``/``positions``
    (S,) int32, ``block_tables`` (S, NB) int32. Fixed shapes — one jitted
    program serves the pool's whole lifetime; idle slots carry all-sentinel
    table rows so their writes are dropped and their (ignored) outputs read
    only clipped garbage."""
    ids = jnp.asarray(ids, jnp.int32)[:, None]                   # (S, 1)
    positions2 = jnp.asarray(positions, jnp.int32)[:, None]
    bts = jnp.asarray(block_tables, jnp.int32)
    vk = _paged_view(cache["k"], bts)
    vv = _paged_view(cache["v"], bts)
    logits, ck, cv = _cached_forward(params, vk, vv, ids, positions2, cfg)
    cache = {"k": _scatter_rows(cache["k"], ck, bts, positions2),
             "v": _scatter_rows(cache["v"], cv, bts, positions2)}
    return logits[:, 0], cache


def copy_kv_blocks(cache: Cache, src: jax.Array, dst: jax.Array) -> Cache:
    """Copy whole pool blocks ``src[i] -> dst[i]`` (both (M,) int32) — the
    allocator's copy-on-write primitive. dst blocks are always private to
    one slot, so indices never collide."""
    src = jnp.asarray(src, jnp.int32)
    dst = jnp.asarray(dst, jnp.int32)
    return {"k": cache["k"].at[:, dst].set(
                jnp.take(cache["k"], src, axis=1)),
            "v": cache["v"].at[:, dst].set(
                jnp.take(cache["v"], src, axis=1))}


def greedy_token(logits: jax.Array) -> jax.Array:
    """argmax over the vocab axis — the default (deterministic) sampler."""
    return jnp.argmax(logits, axis=-1).astype(jnp.int32)


# -- sampling + speculative verify (draft/verify decoding) -------------------
#
# Real sampling (temperature / top-k / top-p) with a COUNTER-BASED key
# discipline: every random draw for a stream is keyed by
# ``fold_in(fold_in(PRNGKey(seed), token_position), role)`` — a pure
# function of (stream seed, absolute position, draw kind), never of
# wall-clock state or round boundaries. That is what keeps sampled streams
# exactly resumable after preemption (worker/generation.py resumes a
# stream by re-prefilling its committed history; the keys for every future
# position are unchanged) and makes speculative rejection-sampling
# well-defined. temperature <= 0 collapses the modified distribution to a
# one-hot argmax, so the greedy path is reproduced bit-identically.
#
# Roles (the third fold_in operand): distinct draw kinds at the same
# position must not share a key, or the accept test would be correlated
# with the proposal it judges.

ROLE_TARGET = 0  # a draw from the target's (modified) distribution
ROLE_DRAFT = 1   # the draft model's proposal draw
ROLE_ACCEPT = 2  # the speculative accept/reject uniform


def _uniform_at(seeds: jax.Array, positions: jax.Array,
                role) -> jax.Array:
    """One uniform in [0, 1) per entry of ``positions``, keyed by the
    counter discipline above. ``seeds``: (S,) uint32 per-slot stream
    seeds; ``positions``: (S,) or (S, T) int32 absolute token positions."""
    seeds = jnp.asarray(seeds, jnp.uint32)
    positions = jnp.asarray(positions, jnp.int32)
    shape = positions.shape
    sb = jnp.broadcast_to(
        seeds.reshape((-1,) + (1,) * (len(shape) - 1)), shape)

    def one(seed, pos):
        key = jax.random.fold_in(
            jax.random.fold_in(jax.random.PRNGKey(seed), pos), role)
        return jax.random.uniform(key)

    return jax.vmap(one)(sb.reshape(-1), positions.reshape(-1)).reshape(shape)


def modified_dist(logits: jax.Array, temperature, top_k, top_p) -> jax.Array:
    """The temperature/top-k/top-p-modified sampling distribution.

    ``logits``: (..., V) f32; the three knobs broadcast against the
    leading shape (per-slot arrays on a batched step). top_k <= 0 and
    top_p >= 1 disable their filters. Rows with temperature <= 0 return
    the exact one-hot of ``argmax(logits)`` — sampling from that
    distribution reproduces :func:`greedy_token` bit-identically, which
    is the invariant speculative verify and preemption-resume rely on."""
    head = logits.shape[:-1]
    v = logits.shape[-1]
    t = jnp.broadcast_to(jnp.asarray(temperature, jnp.float32), head)
    tk = jnp.broadcast_to(jnp.asarray(top_k, jnp.int32), head)
    tp = jnp.broadcast_to(jnp.asarray(top_p, jnp.float32), head)
    greedy = t <= 0.0
    scaled = logits / jnp.where(greedy, 1.0, t)[..., None]
    # top-k: keep each row's k largest logits (ties keep all equal values)
    sorted_desc = jnp.sort(scaled, axis=-1)[..., ::-1]
    k = jnp.clip(jnp.where(tk <= 0, v, tk), 1, v)
    kth = jnp.take_along_axis(sorted_desc, (k - 1)[..., None], axis=-1)
    scaled = jnp.where(scaled >= kth, scaled, -jnp.inf)
    probs = jax.nn.softmax(scaled, axis=-1)
    # top-p: smallest descending-sorted prefix covering mass top_p (the
    # first token is always kept, so the filter never empties a row)
    order = jnp.argsort(-probs, axis=-1)
    sp = jnp.take_along_axis(probs, order, axis=-1)
    keep_sorted = (jnp.cumsum(sp, axis=-1) - sp) < tp[..., None]
    inv = jnp.argsort(order, axis=-1)
    keep = jnp.take_along_axis(keep_sorted, inv, axis=-1)
    probs = probs * keep
    probs = probs / jnp.maximum(jnp.sum(probs, -1, keepdims=True), 1e-20)
    onehot = jax.nn.one_hot(jnp.argmax(logits, axis=-1), v,
                            dtype=jnp.float32)
    return jnp.where(greedy[..., None], onehot, probs)


def sample_from(probs: jax.Array, u: jax.Array) -> jax.Array:
    """Inverse-CDF draw: the smallest index whose cumulative mass exceeds
    ``u``. Exact on one-hot rows (returns the hot index for any u in
    [0, 1)), which is what makes temperature=0 sampling ≡ argmax."""
    c = jnp.cumsum(probs, axis=-1)
    idx = jnp.sum((c <= u[..., None]).astype(jnp.int32), axis=-1)
    return jnp.clip(idx, 0, probs.shape[-1] - 1).astype(jnp.int32)


def _draw(logits: jax.Array, token_positions: jax.Array,
          sampling: Dict[str, jax.Array]
          ) -> Tuple[jax.Array, jax.Array]:
    """(token ids, modified distribution) for a batched single-position
    draw. ``token_positions`` are the ABSOLUTE positions the sampled
    tokens will occupy (write position + 1) — the counter the keys fold."""
    v = logits.shape[-1]

    def _greedy(_):
        am = jnp.argmax(logits, axis=-1).astype(jnp.int32)
        return am, jax.nn.one_hot(am, v, dtype=jnp.float32)

    def _full(_):
        probs = modified_dist(logits, sampling["temperature"],
                              sampling["top_k"], sampling["top_p"])
        u = _uniform_at(sampling["seed"], token_positions,
                        sampling["role"])
        return sample_from(probs, u), probs

    # whole-batch greedy fast path: modified_dist at temperature<=0 IS
    # onehot(argmax) and sample_from(onehot, u) IS the argmax for any u,
    # so skipping the vocab sorts and counter-RNG draws cannot change a
    # single emitted token — it only makes the common greedy table cheap
    all_greedy = jnp.all(
        jnp.asarray(sampling["temperature"], jnp.float32) <= 0.0)
    return jax.lax.cond(all_greedy, _greedy, _full, None)


def decode_step_sampled(params: Params, cache: Cache, ids: jax.Array,
                        positions: jax.Array,
                        sampling: Dict[str, jax.Array], cfg: LMConfig
                        ) -> Tuple[jax.Array, jax.Array, Cache]:
    """:func:`decode_step` + an in-graph sampled draw. Returns
    (token ids (S,), modified distribution (S, V), cache) — the full
    distribution is returned because a draft model's proposal q is the
    denominator of the speculative accept test."""
    logits, cache = decode_step(params, cache, ids, positions, cfg)
    tok, probs = _draw(logits, jnp.asarray(positions, jnp.int32) + 1,
                       sampling)
    return tok, probs, cache


def decode_steps_sampled(params: Params, cache: Cache, ids: jax.Array,
                         positions: jax.Array, k: int,
                         sampling: Dict[str, jax.Array], cfg: LMConfig
                         ) -> Tuple[jax.Array, jax.Array, Cache]:
    """``k`` chained :func:`decode_step_sampled` calls fused into ONE
    program — the draft model's whole proposal burst per speculative
    round. The worker's fallback is k separate jitted calls, each paying
    dispatch plus a host sync to feed the sampled token back in; fusing
    keeps the token feedback in-graph, which is most of a small draft's
    per-round cost on dispatch-bound backends. ``k`` is static (the
    spec-k knob is fixed for a deployment), so the loop unrolls. Returns
    (tokens (S, k), modified distributions q (S, k, V), cache)."""
    ids = jnp.asarray(ids, jnp.int32)
    positions = jnp.asarray(positions, jnp.int32)
    toks, probs = [], []
    for j in range(k):
        ids, pj, cache = decode_step_sampled(params, cache, ids,
                                             positions + j, sampling, cfg)
        toks.append(ids)
        probs.append(pj)
    return jnp.stack(toks, axis=1), jnp.stack(probs, axis=1), cache


def paged_decode_step_sampled(params: Params, cache: Cache, ids: jax.Array,
                              positions: jax.Array, block_tables: jax.Array,
                              sampling: Dict[str, jax.Array], cfg: LMConfig
                              ) -> Tuple[jax.Array, jax.Array, Cache]:
    """:func:`paged_decode_step` + an in-graph sampled draw (see
    :func:`decode_step_sampled`)."""
    logits, cache = paged_decode_step(params, cache, ids, positions,
                                      block_tables, cfg)
    tok, probs = _draw(logits, jnp.asarray(positions, jnp.int32) + 1,
                       sampling)
    return tok, probs, cache


def paged_verify_step(params: Params, cache: Cache, ids: jax.Array,
                      positions: jax.Array, block_tables: jax.Array,
                      draft_probs: jax.Array,
                      sampling: Dict[str, jax.Array], cfg: LMConfig
                      ) -> Tuple[jax.Array, jax.Array, Cache]:
    """Verify k drafted tokens per slot in ONE fixed-shape forward.

    ``ids``: (S, k+1) int32 — column 0 is each slot's last committed
    token, columns 1..k the draft's proposals; ``positions``: (S, k+1)
    the write positions (frontier .. frontier+k); ``draft_probs``:
    (S, k, V) the draft's modified distributions q. Rejection sampling
    (Leviathan et al. / Chen et al.) runs in-graph per slot: draft token
    d_j is accepted iff u_j * q(d_j) < p(d_j) (u_j keyed ROLE_ACCEPT at
    d_j's position), the first rejection resamples from
    norm(max(p - q, 0)), and a fully-accepted row draws a bonus token
    from the k+1-th target distribution — so every round commits
    accept_len + 1 tokens. Per-slot accept lengths are data, not shape:
    mixed acceptance across resident streams never retraces.

    temperature <= 0 rows degrade exactly to greedy: p is one-hot, so a
    draft token is accepted iff it IS the argmax and every correction or
    bonus draw returns the argmax — bit-identical to the plain greedy
    decode loop.

    The K/V written for rejected suffixes need no device-side rollback:
    ``_cached_forward`` writes every new row before attention and the
    causal mask bounds reads at the query's own position, so the next
    round's writes overwrite any stale row before it can be attended.
    Returns (accept_len (S,) int32, tokens (S, k+1) int32 — the committed
    tokens left-packed, entries past accept_len are padding — cache)."""
    ids = jnp.asarray(ids, jnp.int32)
    positions = jnp.asarray(positions, jnp.int32)
    bts = jnp.asarray(block_tables, jnp.int32)
    vk = _paged_view(cache["k"], bts)
    vv = _paged_view(cache["v"], bts)
    logits, ck, cv = _cached_forward(params, vk, vv, ids, positions, cfg)
    cache = {"k": _scatter_rows(cache["k"], ck, bts, positions),
             "v": _scatter_rows(cache["v"], cv, bts, positions)}
    s, k1 = ids.shape
    k = k1 - 1
    d = ids[:, 1:]                                       # (S, k) proposals
    jj = jnp.arange(k1, dtype=jnp.int32)[None, :]
    d_pad = jnp.concatenate([d, jnp.zeros((s, 1), jnp.int32)], axis=1)

    def _greedy(_):
        # whole-batch greedy fast path: p is onehot(argmax), so the
        # accept test collapses to d_j == argmax_j and every correction
        # or bonus draw returns that position's argmax — provably the
        # same tokens as the rejection-sampling branch, minus its vocab
        # sorts and counter-RNG draws
        am = jnp.argmax(logits, axis=-1).astype(jnp.int32)  # (S, k+1)
        accept = d == am[:, :k]
        a = jnp.sum(jnp.cumprod(accept.astype(jnp.int32), axis=-1),
                    axis=-1)
        extra = jnp.take_along_axis(am, a[:, None], axis=1)
        toks = jnp.where(jj < a[:, None], d_pad,
                         jnp.where(jj == a[:, None], extra, 0))
        return a.astype(jnp.int32), toks.astype(jnp.int32)

    def _full(_):
        temp = jnp.asarray(sampling["temperature"], jnp.float32)[:, None]
        top_k = jnp.asarray(sampling["top_k"], jnp.int32)[:, None]
        top_p = jnp.asarray(sampling["top_p"], jnp.float32)[:, None]
        p = modified_dist(logits, temp, top_k, top_p)    # (S, k+1, V)
        q = jnp.asarray(draft_probs, jnp.float32)        # (S, k, V)
        p_head = p[:, :k, :]
        p_d = jnp.take_along_axis(p_head, d[:, :, None], axis=-1)[..., 0]
        q_d = jnp.take_along_axis(q, d[:, :, None], axis=-1)[..., 0]
        u_acc = _uniform_at(sampling["seed"], positions[:, 1:],
                            ROLE_ACCEPT)
        accept = u_acc * q_d < p_d                       # u < min(1, p/q)
        a = jnp.sum(jnp.cumprod(accept.astype(jnp.int32), axis=-1),
                    axis=-1)
        # the replacement draw at every possible rejection point j < k ...
        resid = jnp.maximum(p_head - q, 0.0)
        rs = jnp.sum(resid, axis=-1, keepdims=True)
        corr = jnp.where(rs > 1e-20, resid / jnp.maximum(rs, 1e-20),
                         p_head)
        # ... and the bonus distribution at j == k (all k accepted)
        dist_all = jnp.concatenate([corr, p[:, k:k + 1, :]], axis=1)
        xdist = jnp.take_along_axis(
            dist_all, a[:, None, None], axis=1)[:, 0, :]
        extra_pos = positions[:, 0] + a + 1
        u_x = _uniform_at(sampling["seed"], extra_pos, ROLE_TARGET)
        extra = sample_from(xdist, u_x)
        toks = jnp.where(jj < a[:, None], d_pad,
                         jnp.where(jj == a[:, None], extra[:, None], 0))
        return a.astype(jnp.int32), toks.astype(jnp.int32)

    all_greedy = jnp.all(
        jnp.asarray(sampling["temperature"], jnp.float32) <= 0.0)
    a, toks = jax.lax.cond(all_greedy, _greedy, _full, None)
    return a, toks, cache


def partition_specs(cfg: LMConfig) -> Params:
    return {
        "embed": {"table": P(None, "model")},
        "pos": P(None, None, None),
        "blocks": block_partition_specs(cfg.encoder, stacked=True),
        "ln_f": {"scale": P(None), "bias": P(None)},
    }


def batch_spec() -> Any:
    return (P("data", "seq"), P("data", "seq"))
