"""VGG-style convnet (parity: reference TfVgg16,
examples/models/image_classification/TfVgg16.py:15). NHWC, bf16 compute.
Configurable depth so small inputs (Fashion-MNIST/CIFAR) use a trimmed
stack rather than the full 224x224 architecture."""

from __future__ import annotations

from dataclasses import dataclass
from typing import Any, Dict, Sequence

import jax
import jax.numpy as jnp

from rafiki_tpu.models import core

Params = Dict[str, Any]

VGG16_PLAN: Sequence[Sequence[int]] = (
    (64, 64), (128, 128), (256, 256, 256), (512, 512, 512), (512, 512, 512))
VGG_SMALL_PLAN: Sequence[Sequence[int]] = ((32, 32), (64, 64), (128, 128))


@dataclass(frozen=True)
class VggConfig:
    plan: Sequence[Sequence[int]] = VGG_SMALL_PLAN
    channels: int = 3
    dense_units: int = 256
    num_classes: int = 10


def init(rng: jax.Array, cfg: VggConfig) -> Params:
    keys = iter(jax.random.split(rng, 64))
    params: Params = {"convs": []}
    cin = cfg.channels
    for stage in cfg.plan:
        for cout in stage:
            params["convs"].append(core.conv2d_init(next(keys), 3, 3, cin, cout))
            cin = cout
    params["fc1"] = core.dense_init(next(keys), cin, cfg.dense_units)
    params["head"] = core.dense_init(next(keys), cfg.dense_units,
                                     cfg.num_classes)
    return params


def apply(params: Params, images: jax.Array, cfg: VggConfig) -> jax.Array:
    x = core.cast_for_compute(images)
    i = 0
    for stage in cfg.plan:
        for _ in stage:
            x = jax.nn.relu(core.conv2d(params["convs"][i], x))
            i += 1
        x = jax.lax.reduce_window(
            x, -jnp.inf, jax.lax.max, (1, 2, 2, 1), (1, 2, 2, 1), "VALID")
    x = jnp.mean(x, axis=(1, 2))  # GAP instead of giant fc — same accuracy
    x = jax.nn.relu(core.dense(params["fc1"], x))
    return core.dense(params["head"], x).astype(jnp.float32)
