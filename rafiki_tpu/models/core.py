"""Shared neural-net building blocks over plain dict pytrees.

Design notes (TPU-first):
- Weights are kept in float32 "master" precision; ``cast_for_compute``
  downcasts activations/weights to bfloat16 inside the forward pass so
  matmuls hit the MXU at full rate while the optimizer still sees f32.
- All shapes are static; anything sequence-like is padded by the caller.
- Initializers mirror the usual fan-in scalings (He for conv/relu, Xavier
  for dense/attention) without pulling in a layers framework.
"""

from __future__ import annotations

import math
from typing import Any, Dict, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

Params = Dict[str, Any]


# ---------------------------------------------------------------------------
# initializers
# ---------------------------------------------------------------------------

def xavier_uniform(rng: jax.Array, shape: Sequence[int], in_axis: int = -2,
                   out_axis: int = -1, dtype=jnp.float32,
                   fan_in: Optional[int] = None,
                   fan_out: Optional[int] = None) -> jax.Array:
    """Explicit fan_in/fan_out override the axis-derived fans — used when the
    logical matmul shape differs from the stored param shape (e.g. a
    (dim, heads, dh) projection whose logical fan_out is heads*dh)."""
    if fan_in is None:
        fan_in = shape[in_axis]
    if fan_out is None:
        fan_out = shape[out_axis]
    limit = math.sqrt(6.0 / (fan_in + fan_out))
    return jax.random.uniform(rng, tuple(shape), dtype, -limit, limit)


def he_normal(rng: jax.Array, shape: Sequence[int], fan_in: Optional[int] = None,
              dtype=jnp.float32) -> jax.Array:
    if fan_in is None:
        fan_in = int(np.prod(shape[:-1]))
    std = math.sqrt(2.0 / fan_in)
    return jax.random.normal(rng, tuple(shape), dtype) * std


def normal_init(rng: jax.Array, shape: Sequence[int], std: float = 0.02,
                dtype=jnp.float32) -> jax.Array:
    return jax.random.normal(rng, tuple(shape), dtype) * std


# ---------------------------------------------------------------------------
# layers (init + apply pairs)
# ---------------------------------------------------------------------------

def dense_init(rng: jax.Array, in_dim: int, out_dim: int) -> Params:
    kr, _ = jax.random.split(rng)
    return {
        "kernel": xavier_uniform(kr, (in_dim, out_dim)),
        "bias": jnp.zeros((out_dim,), jnp.float32),
    }


def dense(params: Params, x: jax.Array) -> jax.Array:
    return jnp.dot(x, params["kernel"].astype(x.dtype)) + params["bias"].astype(x.dtype)


def layernorm_init(dim: int) -> Params:
    return {"scale": jnp.ones((dim,), jnp.float32),
            "bias": jnp.zeros((dim,), jnp.float32)}


def layernorm(params: Params, x: jax.Array, eps: float = 1e-6) -> jax.Array:
    # LN statistics in f32 for stability even when x is bf16
    xf = x.astype(jnp.float32)
    mean = jnp.mean(xf, axis=-1, keepdims=True)
    var = jnp.var(xf, axis=-1, keepdims=True)
    y = (xf - mean) * jax.lax.rsqrt(var + eps)
    y = y * params["scale"] + params["bias"]
    return y.astype(x.dtype)


def embedding_init(rng: jax.Array, vocab: int, dim: int, std: float = 0.02) -> Params:
    return {"table": normal_init(rng, (vocab, dim), std)}


def embedding(params: Params, ids: jax.Array, dtype=jnp.bfloat16) -> jax.Array:
    return params["table"].astype(dtype)[ids]


def conv2d_init(rng: jax.Array, kh: int, kw: int, cin: int, cout: int) -> Params:
    return {
        "kernel": he_normal(rng, (kh, kw, cin, cout), fan_in=kh * kw * cin),
        "bias": jnp.zeros((cout,), jnp.float32),
    }


def conv2d(params: Params, x: jax.Array, stride: int = 1,
           padding: str = "SAME") -> jax.Array:
    """NHWC conv — the layout XLA:TPU tiles best onto the MXU."""
    y = jax.lax.conv_general_dilated(
        x, params["kernel"].astype(x.dtype),
        window_strides=(stride, stride), padding=padding,
        dimension_numbers=("NHWC", "HWIO", "NHWC"),
    )
    return y + params["bias"].astype(y.dtype)


def dropout(rng: Optional[jax.Array], x: jax.Array, rate: float,
            deterministic: bool) -> jax.Array:
    if deterministic or rate <= 0.0:
        return x
    assert rng is not None
    keep = jax.random.bernoulli(rng, 1.0 - rate, x.shape)
    return jnp.where(keep, x / (1.0 - rate), 0.0).astype(x.dtype)


def cast_for_compute(x: jax.Array, dtype=jnp.bfloat16) -> jax.Array:
    return x.astype(dtype)


# ---------------------------------------------------------------------------
# pytree helpers
# ---------------------------------------------------------------------------

def split_keys(rng: jax.Array, n: int) -> Tuple[jax.Array, ...]:
    return tuple(jax.random.split(rng, n))


def stack_layers(layer_params: Sequence[Params]) -> Params:
    """Stack per-layer param pytrees along a new leading axis so the forward
    pass can ``lax.scan`` over layers — one compiled block body regardless of
    depth (compile time O(1) in depth, and the natural layout for pipeline
    parallelism: shard the leading axis over the ``pipe`` mesh axis)."""
    return jax.tree.map(lambda *xs: jnp.stack(xs), *layer_params)


def param_count(params: Any) -> int:
    return sum(int(np.prod(p.shape)) for p in jax.tree.leaves(params))
