"""BERT-style encoder for text classification.

Backs the BASELINE.json "BERT-base text classification with search"
config: a bidirectional transformer encoder (models/transformer.py stack,
non-causal) with token/position embeddings and first-token pooling.
Architecture search runs through the standard advisor machinery: the
JaxBert template (examples/models/text_classification/JaxBert.py) exposes
depth/heads/dim as knobs, so the shared GP advisor samples architectures
of this family as trials.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Dict, Optional, Tuple

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from rafiki_tpu.models import core
from rafiki_tpu.models.transformer import (
    TransformerConfig,
    block_partition_specs,
    stack_apply,
    stack_init,
)

Params = Dict[str, Any]


@dataclass(frozen=True)
class BertConfig:
    vocab: int = 30522
    max_len: int = 512
    num_classes: int = 2
    encoder: TransformerConfig = field(default_factory=TransformerConfig)


def bert_base(num_classes: int = 2) -> BertConfig:
    return BertConfig(num_classes=num_classes,
                      encoder=TransformerConfig(dim=768, depth=12, heads=12))


def tiny(vocab: int = 1000, max_len: int = 64, num_classes: int = 2,
         dim: int = 64, depth: int = 2, heads: int = 4) -> BertConfig:
    return BertConfig(vocab=vocab, max_len=max_len, num_classes=num_classes,
                      encoder=TransformerConfig(dim=dim, depth=depth,
                                                heads=heads))


def init(rng: jax.Array, cfg: BertConfig) -> Params:
    k_emb, k_pos, k_blocks, k_pool, k_head = jax.random.split(rng, 5)
    return {
        "embed": core.embedding_init(k_emb, cfg.vocab, cfg.encoder.dim),
        "pos": core.normal_init(k_pos, (1, cfg.max_len, cfg.encoder.dim)),
        "blocks": stack_init(k_blocks, cfg.encoder),
        "ln_f": core.layernorm_init(cfg.encoder.dim),
        "pool": core.dense_init(k_pool, cfg.encoder.dim, cfg.encoder.dim),
        "head": core.dense_init(k_head, cfg.encoder.dim, cfg.num_classes),
    }


def apply(params: Params, ids: jax.Array, cfg: BertConfig,
          rng: Optional[jax.Array] = None,
          deterministic: bool = True) -> jax.Array:
    """ids: (B, S) int32 -> logits (B, num_classes)."""
    s = ids.shape[1]
    x = core.embedding(params["embed"], ids)
    x = x + params["pos"][:, :s, :].astype(x.dtype)
    x, _ = stack_apply(params["blocks"], x, cfg.encoder, rng, deterministic)
    x = core.layernorm(params["ln_f"], x)
    pooled = jnp.tanh(core.dense(params["pool"], x[:, 0]))
    return core.dense(params["head"], pooled).astype(jnp.float32)


def partition_specs(cfg: BertConfig) -> Params:
    return {
        "embed": {"table": P(None, "model")},
        "pos": P(None, None, None),
        "blocks": block_partition_specs(cfg.encoder, stacked=True),
        "ln_f": {"scale": P(None), "bias": P(None)},
        "pool": {"kernel": P(None, "model"), "bias": P("model")},
        "head": {"kernel": P(None, None), "bias": P(None)},
    }


def batch_spec() -> Any:
    return P("data", None)
