"""Framework-wide enums and constants.

Capability parity with the reference's rafiki/constants.py (user types, budget
types, task types incl. the fork's IMAGE_GENERATION, and the job/trial/service
status machines at reference rafiki/constants.py:16-62), expressed as plain
string-valued classes so values JSON-serialize transparently.
"""


class UserType:
    SUPERADMIN = "SUPERADMIN"
    ADMIN = "ADMIN"
    MODEL_DEVELOPER = "MODEL_DEVELOPER"
    APP_DEVELOPER = "APP_DEVELOPER"


class BudgetType:
    # Number of trials to run per model (reference BudgetType.MODEL_TRIAL_COUNT).
    MODEL_TRIAL_COUNT = "MODEL_TRIAL_COUNT"
    # Chip budget for a train job: how many TPU chips (reference: GPU_COUNT).
    CHIP_COUNT = "CHIP_COUNT"
    # Accepted alias so reference-style budgets keep working.
    GPU_COUNT = "GPU_COUNT"
    # Wall-clock budget in hours (new capability; the reference has none).
    TIME_HOURS = "TIME_HOURS"
    # Chips granted to EACH trial executor (new capability): >1 gives every
    # trial a multi-chip mesh — data/tensor/sequence-parallel training inside
    # a trial, not just trial-parallelism. The reference was hard-wired to
    # 1 GPU per worker (reference services_manager.py:117-126).
    CHIPS_PER_TRIAL = "CHIPS_PER_TRIAL"
    # ASHA early stopping (new capability; reference trials always ran to
    # their full epoch budget). Truthy enables rung-based stopping on the
    # per-epoch "loss" metric templates already log; min-epochs/eta tune
    # the rung ladder (advisor/asha.py).
    EARLY_STOP = "EARLY_STOP"
    ASHA_MIN_EPOCHS = "ASHA_MIN_EPOCHS"
    ASHA_ETA = "ASHA_ETA"
    # Per-trial wall-clock cap in seconds (new capability): a trial that
    # exceeds it is truncated at its next metrics report and completes with
    # the score its partial training earned — a runaway knob draw cannot
    # hold an executor forever.
    TRIAL_TIMEOUT_S = "TRIAL_TIMEOUT_S"
    # Vectorized trial execution (new capability): proposals drained per
    # vmapped training round for templates advertising a PopulationSpec
    # — overrides RAFIKI_TRIAL_VMAP_K for this job. The worker trains
    # each shape-compatible bucket of that many proposals as ONE
    # PopulationTrainer program on its chip grant (worker/train.py;
    # docs/performance.md "Vectorized trial execution").
    TRIAL_VMAP_K = "TRIAL_VMAP_K"
    # Chips granted to EACH inference worker (new capability): >1 gives a
    # serving executor a multi-chip mesh, so a model too big (or too slow)
    # for one chip serves its pjit'd predict sharded over ICI — the serving
    # analogue of CHIPS_PER_TRIAL. Passed in create_inference_job's budget.
    CHIPS_PER_WORKER = "CHIPS_PER_WORKER"
    # Fused ensemble serving (new capability): truthy deploys ONE worker
    # (xN replicas) holding ALL best trials co-resident in HBM instead of
    # a worker fleet per trial. When the trials share a compiled predict
    # (same template, same architecture knobs), the whole ensemble answers
    # in a single vmapped device dispatch (SURVEY §7 "ensembles across
    # trials on one chip set"); otherwise the fused worker still serves
    # them sequentially in-process. Passed in create_inference_job's
    # budget.
    ENSEMBLE_FUSED = "ENSEMBLE_FUSED"
    # Speculative decoding (generation jobs only): the trial id of a small
    # DRAFT language model that proposes k tokens per scheduler round for
    # the deployed target to verify in one fixed-shape forward
    # (docs/serving-generation.md "Speculative decoding & sampling").
    # Passed in create_inference_job's budget; validated at deploy time
    # (the trial must exist and be generation-capable) and loaded by every
    # generation worker of the job.
    GEN_DRAFT_TRIAL = "GEN_DRAFT_TRIAL"


class TaskType:
    IMAGE_CLASSIFICATION = "IMAGE_CLASSIFICATION"
    POS_TAGGING = "POS_TAGGING"
    # Present only in the vivansxu fork (reference rafiki/constants.py:62).
    IMAGE_GENERATION = "IMAGE_GENERATION"
    TEXT_CLASSIFICATION = "TEXT_CLASSIFICATION"
    # Token-streaming generative serving (new capability; no reference
    # analogue): templates must advertise a fully-wired GenerationSpec
    # (sdk/model.py), inference workers run the continuous-batching
    # decode loop (worker/generation.py), and the dedicated predictor
    # door streams deltas (docs/serving-generation.md). Task/capability
    # consistency is validated at model upload AND train-job creation —
    # a generative template on a classification job (or vice versa) is a
    # typed 400, never a trial-time crash.
    TEXT_GENERATION = "TEXT_GENERATION"


class ModelDependency:
    # Declared model deps map to install actions in the reference
    # (rafiki/model/model.py:244-273); on TPU the JAX stack is ambient, so
    # these are recorded for provenance and validated rather than pip-installed
    # per worker boot (which the reference did at scripts/start_worker.py:6-9).
    JAX = "jax"
    FLAX = "flax"
    OPTAX = "optax"
    TENSORFLOW = "tensorflow"
    TORCH = "torch"
    SCIKIT_LEARN = "scikit-learn"
    NUMPY = "numpy"


class TrainJobStatus:
    STARTED = "STARTED"
    RUNNING = "RUNNING"
    STOPPED = "STOPPED"
    ERRORED = "ERRORED"


class TrialStatus:
    STARTED = "STARTED"
    RUNNING = "RUNNING"
    COMPLETED = "COMPLETED"
    ERRORED = "ERRORED"
    TERMINATED = "TERMINATED"


class InferenceJobStatus:
    STARTED = "STARTED"
    RUNNING = "RUNNING"
    STOPPED = "STOPPED"
    ERRORED = "ERRORED"


class ServiceStatus:
    STARTED = "STARTED"
    DEPLOYING = "DEPLOYING"
    RUNNING = "RUNNING"
    STOPPED = "STOPPED"
    ERRORED = "ERRORED"


class ServiceType:
    TRAIN = "TRAIN"
    INFERENCE = "INFERENCE"
    PREDICT = "PREDICT"
    ADVISOR = "ADVISOR"


class RolloutPhase:
    # Safe live rollouts (admin/rollout.py; docs/failure-model.md
    # "Rollout faults"): a RUNNING inference job is updated to a new
    # trial/model version in place — canary first, then a rolling
    # replace — with automatic rollback on SLO breach, canary crash, or
    # deploy timeout. CANARY/ROLLING are the live phases (exactly one
    # rollout may be in flight per job); DONE/ROLLED_BACK/ABORTED are
    # terminal. ABORTED = the rollout ended without a rollback pass
    # (job stopped/errored, or a dead admin's stale row swept at boot).
    CANARY = "CANARY"
    ROLLING = "ROLLING"
    DONE = "DONE"
    ROLLED_BACK = "ROLLED_BACK"
    ABORTED = "ABORTED"

    LIVE = (CANARY, ROLLING)
    TERMINAL = (DONE, ROLLED_BACK, ABORTED)


class DriftPhase:
    # Drift closed loop (admin/drift.py; docs/failure-model.md "Model
    # drift faults"): per-RUNNING-inference-job state machine persisted
    # in the drift_state table. WATCHING = monitoring the serving plane
    # against a frozen baseline window; RETRAINING = one bounded
    # warm-started retrain is in flight (retrain_job_id is the
    # idempotency key — recovery never launches a second); ROLLING_OUT =
    # a better-scoring candidate is going through the SLO-judged rollout;
    # COOLDOWN = backing off until cooldown_until (rollback/worse
    # candidate/noisy signal); PARKED = the loop gave up (launch retries
    # exhausted, state unreconcilable after a crash) and waits for an
    # operator ack to re-arm. RETRAINING/ROLLING_OUT are the phases
    # ControlPlaneRecovery must resume after an admin crash.
    WATCHING = "WATCHING"
    RETRAINING = "RETRAINING"
    ROLLING_OUT = "ROLLING_OUT"
    COOLDOWN = "COOLDOWN"
    PARKED = "PARKED"

    LIVE = (RETRAINING, ROLLING_OUT)


class AgentHealth:
    # Heartbeat-derived state of a host agent (placement/hosts.py monitor;
    # docs/failure-model.md). UNKNOWN = not probed yet.
    UNKNOWN = "UNKNOWN"
    UP = "UP"
    DOWN = "DOWN"


class ModelAccessRight:
    PUBLIC = "PUBLIC"
    PRIVATE = "PRIVATE"


class AdvisorType:
    # Native Gaussian-process Bayesian optimization (replaces the reference's
    # BTB GP advisor, reference rafiki/advisor/btb_gp_advisor.py).
    GP = "GP"
    RANDOM = "RANDOM"
