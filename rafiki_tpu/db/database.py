"""Data-access layer over SQLite or PostgreSQL.

Same relational shape as the reference's PostgreSQL schema (reference
rafiki/db/schema.py:18-133 — user, model, train_job, sub_train_job,
train_job_worker, inference_job, inference_job_worker, trial, trial_log,
service) and the same DAL surface style as reference rafiki/db/database.py
(~50 query/mutation methods, status-transition helpers).

Backend selection is by connection string (the reference's seam, reference
db/database.py:20-34): a filesystem path (or ``:memory:``) selects the
embedded SQLite/WAL backend — the dev and single-host default, usable
in-process from every worker thread/process on one machine — while a
``postgresql://`` URL selects an external PostgreSQL server for multi-host
control planes (requires ``psycopg2``; driven by ``RAFIKI_DB_URL``). The
SQL in this module is written once in the portable subset and translated
per backend (placeholders, reserved words, DDL types).

Thread-safe via a single serialized connection guarded by an RLock.
"""

from __future__ import annotations

import json
import os
import sqlite3
import threading
import time
import uuid
from typing import Any, Dict, List, Optional

from rafiki_tpu import config
from rafiki_tpu.constants import (
    InferenceJobStatus,
    RolloutPhase,
    ServiceStatus,
    TrainJobStatus,
    TrialStatus,
)
from rafiki_tpu.utils import chaos


class MetadataStoreChaosError(RuntimeError):
    """Chaos-injected transient store failure (RAFIKI_CHAOS site=db) —
    the drillable stand-in for a flaky/contended metadata store during
    control-plane recovery (docs/failure-model.md)."""


class StaleEpochError(RuntimeError):
    """A mutating control-plane write was refused by the epoch fence
    (docs/failure-model.md "Control-plane HA"): either a newer admin has
    acquired the leadership lease (this writer's epoch is stale), or this
    writer could not renew its own lease within the TTL and self-fenced.
    Terminal for the caller — a fenced ex-leader must stop mutating, not
    retry."""

    def __init__(self, message: str, expected: Optional[int] = None,
                 current: Optional[int] = None):
        super().__init__(message)
        self.expected = expected
        self.current = current

# the single control-plane leadership lease row (control_lease, r20)
LEASE_ID = "admin"

# NOTE: tables are ordered so every REFERENCES target exists before its
# referrer — PostgreSQL validates foreign keys at CREATE TABLE time
# (SQLite only at DML time).
_SCHEMA = """
CREATE TABLE IF NOT EXISTS "user" (
    id TEXT PRIMARY KEY,
    email TEXT NOT NULL UNIQUE,
    password_hash TEXT NOT NULL,
    user_type TEXT NOT NULL,
    banned INTEGER NOT NULL DEFAULT 0,
    datetime_created REAL NOT NULL
);
CREATE TABLE IF NOT EXISTS model (
    id TEXT PRIMARY KEY,
    user_id TEXT NOT NULL REFERENCES "user"(id),
    name TEXT NOT NULL,
    task TEXT NOT NULL,
    model_file_bytes BLOB NOT NULL,
    model_class TEXT NOT NULL,
    dependencies TEXT NOT NULL,
    access_right TEXT NOT NULL,
    verification TEXT,
    datetime_created REAL NOT NULL,
    UNIQUE (name, user_id)
);
CREATE TABLE IF NOT EXISTS train_job (
    id TEXT PRIMARY KEY,
    user_id TEXT NOT NULL REFERENCES "user"(id),
    app TEXT NOT NULL,
    app_version INTEGER NOT NULL,
    task TEXT NOT NULL,
    train_dataset_uri TEXT NOT NULL,
    test_dataset_uri TEXT NOT NULL,
    budget TEXT NOT NULL,
    status TEXT NOT NULL,
    fault_kind TEXT,
    error_reason TEXT,
    datetime_started REAL NOT NULL,
    datetime_stopped REAL,
    UNIQUE (app, app_version, user_id)
);
CREATE TABLE IF NOT EXISTS sub_train_job (
    id TEXT PRIMARY KEY,
    train_job_id TEXT NOT NULL REFERENCES train_job(id),
    model_id TEXT NOT NULL REFERENCES model(id),
    advisor_id TEXT
);
CREATE TABLE IF NOT EXISTS service (
    id TEXT PRIMARY KEY,
    service_type TEXT NOT NULL,
    status TEXT NOT NULL,
    replicas INTEGER NOT NULL DEFAULT 1,
    chips TEXT NOT NULL DEFAULT '[]',
    host TEXT,
    port INTEGER,
    pid INTEGER,
    datetime_started REAL NOT NULL,
    datetime_stopped REAL
);
CREATE INDEX IF NOT EXISTS idx_service_status ON service(status);
CREATE TABLE IF NOT EXISTS trial (
    id TEXT PRIMARY KEY,
    sub_train_job_id TEXT NOT NULL REFERENCES sub_train_job(id),
    model_id TEXT NOT NULL REFERENCES model(id),
    worker_id TEXT,
    knobs TEXT NOT NULL,
    score REAL,
    status TEXT NOT NULL,
    params_file_path TEXT,
    attempt INTEGER NOT NULL DEFAULT 0,
    fault_kind TEXT,
    fault_detail TEXT,
    datetime_started REAL NOT NULL,
    datetime_stopped REAL
);
CREATE TABLE IF NOT EXISTS train_job_worker (
    service_id TEXT PRIMARY KEY REFERENCES service(id),
    sub_train_job_id TEXT NOT NULL REFERENCES sub_train_job(id)
);
CREATE TABLE IF NOT EXISTS inference_job (
    id TEXT PRIMARY KEY,
    user_id TEXT NOT NULL REFERENCES "user"(id),
    train_job_id TEXT NOT NULL REFERENCES train_job(id),
    status TEXT NOT NULL,
    predictor_service_id TEXT,
    budget TEXT,
    datetime_started REAL NOT NULL,
    datetime_stopped REAL
);
CREATE TABLE IF NOT EXISTS inference_job_worker (
    service_id TEXT PRIMARY KEY REFERENCES service(id),
    inference_job_id TEXT NOT NULL REFERENCES inference_job(id),
    trial_id TEXT NOT NULL REFERENCES trial(id),
    model_version INTEGER NOT NULL DEFAULT 0,
    borrowed_chips INTEGER NOT NULL DEFAULT 0,
    standby INTEGER NOT NULL DEFAULT 0
);
CREATE TABLE IF NOT EXISTS rollout (
    id TEXT PRIMARY KEY,
    inference_job_id TEXT NOT NULL REFERENCES inference_job(id),
    from_trial_id TEXT,
    to_trial_id TEXT NOT NULL,
    from_version INTEGER NOT NULL,
    to_version INTEGER NOT NULL,
    n_replicas_before INTEGER NOT NULL DEFAULT 0,
    phase TEXT NOT NULL,
    reason TEXT,
    events TEXT NOT NULL DEFAULT '[]',
    operator_ack INTEGER NOT NULL DEFAULT 0,
    datetime_started REAL NOT NULL,
    datetime_stopped REAL
);
CREATE TABLE IF NOT EXISTS drift_state (
    inference_job_id TEXT PRIMARY KEY REFERENCES inference_job(id),
    phase TEXT NOT NULL,
    reason TEXT,
    baseline TEXT,
    signals TEXT,
    retrain_job_id TEXT,
    candidate_trial_id TEXT,
    cooldown_until REAL NOT NULL DEFAULT 0,
    consecutive_rollbacks INTEGER NOT NULL DEFAULT 0,
    events TEXT NOT NULL DEFAULT '[]',
    operator_ack INTEGER NOT NULL DEFAULT 0,
    datetime_updated REAL NOT NULL
);
CREATE TABLE IF NOT EXISTS trial_log (
    id INTEGER PRIMARY KEY AUTOINCREMENT,
    trial_id TEXT NOT NULL REFERENCES trial(id),
    line TEXT NOT NULL,
    datetime REAL NOT NULL
);
CREATE INDEX IF NOT EXISTS idx_trial_log_trial ON trial_log(trial_id);
CREATE TABLE IF NOT EXISTS control_lease (
    id TEXT PRIMARY KEY,
    holder TEXT NOT NULL,
    addr TEXT,
    epoch INTEGER NOT NULL,
    expires_at REAL NOT NULL,
    datetime_updated REAL NOT NULL
);
"""


def translate_placeholders(sql: str) -> str:
    """Portable ``?`` placeholders -> psycopg2 ``%s``.

    The DAL's portable SQL never puts a literal ``?`` or ``%`` inside a
    string literal (tests/test_db_dialect.py lints every statement the DAL
    can issue), so a plain replace is exact — no quote-aware scanning
    needed at runtime on the hot path.
    """
    return sql.replace("?", "%s")


def translate_ddl(schema_sql: str) -> str:
    """The embedded schema's SQLite DDL types -> PostgreSQL equivalents.
    Kept as data-driven string rewrites so the conformance tests can
    assert the full mapping without a live server (VERDICT r3 weak #4)."""
    for src, dst in DDL_TYPE_MAP:
        schema_sql = schema_sql.replace(src, dst)
    return schema_sql


# ordered: AUTOINCREMENT must rewrite before bare INTEGER would ever be
# considered; REAL after BIGSERIAL so nothing re-matches
DDL_TYPE_MAP = (
    ("BLOB", "BYTEA"),
    ("INTEGER PRIMARY KEY AUTOINCREMENT", "BIGSERIAL PRIMARY KEY"),
    ("REAL", "DOUBLE PRECISION"),
)


class _SqliteBackend:
    """Embedded backend: SQLite in WAL mode, single serialized connection."""

    kind = "sqlite"

    def __init__(self, path: str):
        self.path = path
        if path != ":memory:":
            os.makedirs(os.path.dirname(os.path.abspath(path)), exist_ok=True)
        self.conn = sqlite3.connect(
            path, check_same_thread=False, isolation_level=None
        )
        self.conn.row_factory = sqlite3.Row
        self.conn.execute("PRAGMA journal_mode=WAL")
        self.conn.execute("PRAGMA foreign_keys=ON")
        # Cross-process story (ProcessPlacementManager): every worker
        # process opens its own Database on the same WAL file; concurrent
        # writers serialize on the file lock, waiting up to this budget
        # instead of failing with 'database is locked'.
        self.conn.execute("PRAGMA busy_timeout=15000")
        self.conn.executescript(_SCHEMA)
        if path != ":memory:":
            # owner-only: the metadata store is part of the sandbox
            # protection boundary (sdk/sandbox.py threat model) — jailed
            # model code must not be able to read or edit it. WAL/-shm
            # sidecars inherit these bits from sqlite.
            try:
                os.chmod(path, 0o600)
            except OSError:
                pass

    def execute(self, sql: str, args: tuple = ()):
        return self.conn.execute(sql, args)

    @staticmethod
    def to_dict(row) -> Dict[str, Any]:
        return dict(row)

    def begin_exclusive(self, key: str) -> None:
        """Open a transaction that serializes concurrent writers. IMMEDIATE
        takes the database write lock up front, so a read inside the
        transaction can't be invalidated before a following write."""
        self.conn.execute("BEGIN IMMEDIATE")

    def commit(self) -> None:
        self.conn.execute("COMMIT")

    def rollback(self) -> None:
        self.conn.execute("ROLLBACK")

    def close(self) -> None:
        self.conn.close()


class _PostgresBackend:
    """External-server backend for multi-host control planes (the
    reference's default, reference db/database.py:20-34). Translates the
    module's portable SQL: ``?`` placeholders -> ``%s`` and DDL types."""

    kind = "postgres"

    def __init__(self, url: str):
        try:
            import psycopg2
            import psycopg2.extras
        except ImportError as e:  # pragma: no cover - env without the driver
            raise RuntimeError(
                "postgresql:// store requires the psycopg2 driver "
                "(pip install psycopg2-binary)") from e
        self.path = url
        self._dict_cursor = psycopg2.extras.RealDictCursor
        self.conn = psycopg2.connect(url)
        # autocommit parity with the sqlite backend: statements stand alone
        # unless an explicit BEGIN opens a transaction block
        self.conn.autocommit = True
        cur = self.conn.cursor()
        # serialize DDL across simultaneous boots: PG's CREATE TABLE IF NOT
        # EXISTS is not concurrency-safe (two sessions can race into a
        # duplicate-key error on pg_type), so take a session advisory lock
        # for the schema pass
        cur.execute("SELECT pg_advisory_lock(hashtext('rafiki_schema'))")
        try:
            cur.execute(translate_ddl(_SCHEMA))
        finally:
            cur.execute("SELECT pg_advisory_unlock(hashtext('rafiki_schema'))")

    def execute(self, sql: str, args: tuple = ()):
        cur = self.conn.cursor(cursor_factory=self._dict_cursor)
        cur.execute(translate_placeholders(sql), args)
        return cur

    @staticmethod
    def to_dict(row) -> Dict[str, Any]:
        # BYTEA arrives as memoryview; the DAL contract is bytes
        return {
            k: bytes(v) if isinstance(v, memoryview) else v
            for k, v in dict(row).items()
        }

    def begin_exclusive(self, key: str) -> None:
        """Transaction-scoped advisory lock on the key: concurrent
        reserve-style writers for the same key serialize, unrelated keys
        proceed in parallel."""
        cur = self.conn.cursor()
        cur.execute("BEGIN")
        try:
            cur.execute("SELECT pg_advisory_xact_lock(hashtext(%s))", (key,))
        except Exception:
            # never leave the shared connection inside an aborted
            # transaction block — every later statement would fail
            self.rollback()
            raise

    def commit(self) -> None:
        self.conn.cursor().execute("COMMIT")

    def rollback(self) -> None:
        self.conn.cursor().execute("ROLLBACK")

    def close(self) -> None:
        self.conn.close()


def _make_backend(conn_str: str):
    if conn_str.startswith(("postgresql://", "postgres://")):
        return _PostgresBackend(conn_str)
    return _SqliteBackend(conn_str)


class Database:
    """DAL facade. One instance may be shared across threads.

    ``db_path`` is a connection string: a filesystem path / ``:memory:``
    (SQLite) or a ``postgresql://`` URL. Default:
    ``RAFIKI_DB_URL`` env if set, else the workdir SQLite file."""

    def __init__(self, db_path: Optional[str] = None):
        # config.DB_PATH already resolves RAFIKI_DB_URL over RAFIKI_DB_PATH
        conn_str = db_path or config.DB_PATH
        self._lock = threading.RLock()
        self._b = _make_backend(conn_str)
        # epoch write-fence (control-plane HA, admin/lease.py): when armed
        # (a leader holds the leadership lease through this handle), every
        # mutating statement first proves — under the same lock — that the
        # lease row still carries this epoch AND that the lease was renewed
        # within its TTL. Disarmed (None) for non-HA deployments: zero
        # overhead on the write path.
        self._fence_epoch: Optional[int] = None  # guarded-by: _lock
        self._fence_valid_until = 0.0  # guarded-by: _lock (monotonic)
        self._migrate()

    # additive migrations for stores created by earlier versions — the
    # CREATE TABLE IF NOT EXISTS schema pass never alters existing tables
    _MIGRATIONS = (
        # r5: inference jobs gained a serving budget (CHIPS_PER_WORKER)
        "ALTER TABLE inference_job ADD COLUMN budget TEXT",
        # r6 (control-plane recovery): worker-process pid, so a restarted
        # admin can adopt (or fence) surviving local children, plus an
        # index backing the recovery scan's status predicate
        "ALTER TABLE service ADD COLUMN pid INTEGER",
        "CREATE INDEX IF NOT EXISTS idx_service_status ON service(status)",
        # r7 (trial fault taxonomy): why a trial/job failed, queryable —
        # attempt counts infra-class re-runs under the same trial id
        "ALTER TABLE trial ADD COLUMN attempt INTEGER NOT NULL DEFAULT 0",
        "ALTER TABLE trial ADD COLUMN fault_kind TEXT",
        "ALTER TABLE trial ADD COLUMN fault_detail TEXT",
        "ALTER TABLE train_job ADD COLUMN fault_kind TEXT",
        "ALTER TABLE train_job ADD COLUMN error_reason TEXT",
        # r9 (static analysis): the template verifier's report persists
        # on the model row (JSON); NULL = uploaded before the verifier
        # or under RAFIKI_VERIFY_TEMPLATES=off (doctor lists those)
        "ALTER TABLE model ADD COLUMN verification TEXT",
        # r11 (safe live rollouts): which model version a serving replica
        # runs — a rollout deploys new-version replicas beside the
        # incumbents, so recovery can reconstruct a mixed-version fleet
        # (admin/rollout.py; docs/failure-model.md "Rollout faults")
        "ALTER TABLE inference_job_worker ADD COLUMN"
        " model_version INTEGER NOT NULL DEFAULT 0",
        # r11: rollout rows (the CREATE TABLE in _SCHEMA covers fresh
        # stores; this covers stores created by earlier versions)
        """CREATE TABLE IF NOT EXISTS rollout (
    id TEXT PRIMARY KEY,
    inference_job_id TEXT NOT NULL REFERENCES inference_job(id),
    from_trial_id TEXT,
    to_trial_id TEXT NOT NULL,
    from_version INTEGER NOT NULL,
    to_version INTEGER NOT NULL,
    n_replicas_before INTEGER NOT NULL DEFAULT 0,
    phase TEXT NOT NULL,
    reason TEXT,
    events TEXT NOT NULL DEFAULT '[]',
    operator_ack INTEGER NOT NULL DEFAULT 0,
    datetime_started REAL NOT NULL,
    datetime_stopped REAL
)""",
        # r16 (drift closed loop): the chip-loan marker — how many chips
        # this serving replica borrowed from the training floor, so a
        # restarted admin can rebuild the in-memory loan book instead of
        # leaking the loan forever (admin/recovery.py; the PR 7
        # restart limitation)
        "ALTER TABLE inference_job_worker ADD COLUMN"
        " borrowed_chips INTEGER NOT NULL DEFAULT 0",
        # r16: drift loop state (admin/drift.py) — one mutable row per
        # inference job; retrain_job_id is the idempotency key that
        # keeps a recovered admin from double-launching a retrain
        """CREATE TABLE IF NOT EXISTS drift_state (
    inference_job_id TEXT PRIMARY KEY REFERENCES inference_job(id),
    phase TEXT NOT NULL,
    reason TEXT,
    baseline TEXT,
    signals TEXT,
    retrain_job_id TEXT,
    candidate_trial_id TEXT,
    cooldown_until REAL NOT NULL DEFAULT 0,
    consecutive_rollbacks INTEGER NOT NULL DEFAULT 0,
    events TEXT NOT NULL DEFAULT '[]',
    operator_ack INTEGER NOT NULL DEFAULT 0,
    datetime_updated REAL NOT NULL
)""",
        # r17 (cold-start resilience): warm standby replicas — pre-loaded
        # and pre-warmed but NOT routed (predictor add_worker is deferred
        # to promotion). The durable flag lets a restarted admin rebuild
        # the standby registry and keep standbys out of the routable set
        # during adoption (admin/warm_pool.py; docs/failure-model.md
        # "Cold-start faults")
        "ALTER TABLE inference_job_worker ADD COLUMN"
        " standby INTEGER NOT NULL DEFAULT 0",
        # r20 (control-plane HA): the leadership lease — ONE row (id
        # 'admin') whose monotonic epoch bumps on every acquisition.
        # Acquire/renew are compare-and-set under the backend's exclusive
        # transaction, and the epoch fences every mutating write of a
        # leader that lost it (admin/lease.py; docs/failure-model.md
        # "Control-plane HA")
        """CREATE TABLE IF NOT EXISTS control_lease (
    id TEXT PRIMARY KEY,
    holder TEXT NOT NULL,
    addr TEXT,
    epoch INTEGER NOT NULL,
    expires_at REAL NOT NULL,
    datetime_updated REAL NOT NULL
)""",
    )

    def _migrate(self) -> None:
        for stmt in self._MIGRATIONS:
            if self._b.kind == "postgres":
                # migration DDL needs the same type mapping the schema
                # gets (REAL is float4 on PG — epoch seconds would lose
                # sub-minute precision)
                stmt = translate_ddl(stmt)
            with self._lock:
                try:
                    self._b.execute(stmt)
                except Exception as e:
                    # duplicate-column: the store is already current
                    # (both backends run statement-at-a-time autocommit,
                    # so a failed ALTER leaves no broken transaction).
                    # Anything ELSE is a real failure and must stay loud
                    # — a silently missing column would surface later as
                    # a confusing unrelated error.
                    msg = str(e).lower()
                    if not ("duplicate column" in msg
                            or "already exists" in msg):
                        raise

    @property
    def path(self) -> str:
        """The backing connection string (':memory:' for the in-memory
        store; a postgresql:// URL for the server backend)."""
        return self._b.path

    @property
    def backend(self) -> str:
        return self._b.kind

    def close(self) -> None:
        with self._lock:
            self._b.close()

    # -- low-level helpers -------------------------------------------------

    @staticmethod
    def _chaos(sql: str) -> None:
        """RAFIKI_CHAOS site=db: deterministic transient-store faults,
        injected before the statement reaches the backend (match =
        the SQL text). `delay` models a slow store; `error`/`drop` raise
        the typed transient failure callers retry on."""
        rule = chaos.hit(chaos.SITE_DB, sql)
        if rule is None:
            return
        if rule.action == chaos.ACTION_DELAY:
            chaos.sleep_for(rule)
            return
        raise MetadataStoreChaosError(
            f"chaos-injected metadata-store fault on {sql.split(None, 1)[0]}")

    # statements the epoch fence guards; DDL only runs at migrate time
    # (before any fence is armed) and SELECTs are always safe to serve
    _MUTATING_VERBS = ("INSERT", "UPDATE", "DELETE")

    def _fence_check_locked(self) -> None:  # guarded-by: _lock
        """Guarded compare-and-set half of epoch fencing: called with the
        handle lock held, immediately before a mutating statement (or
        inside an exclusive transaction). Raises StaleEpochError when this
        writer's lease lapsed (self-fence — renewal missed its TTL) or a
        newer epoch holds the lease row."""
        epoch = self._fence_epoch
        if epoch is None:
            return
        if time.monotonic() >= self._fence_valid_until:
            raise StaleEpochError(
                f"self-fenced: leadership lease (epoch {epoch}) was not "
                "renewed within its TTL; refusing to mutate the store",
                expected=epoch)
        row = self._b.execute(
            "SELECT epoch FROM control_lease WHERE id=?", (LEASE_ID,)
        ).fetchone()
        current = row["epoch"] if row else 0
        if current != epoch:
            raise StaleEpochError(
                f"stale epoch {epoch}: the leadership lease is now held at "
                f"epoch {current}; this admin must stop mutating",
                expected=epoch, current=current)

    def set_fence(self, epoch: int, valid_until: float) -> None:
        """Arm/refresh the epoch write-fence. ``valid_until`` is a
        ``time.monotonic()`` deadline — each successful lease renewal
        extends it by the TTL, so a SIGSTOP'd/partitioned leader that
        resumes past the TTL self-fences on its next write even before
        the standby has taken the lease row over."""
        with self._lock:
            self._fence_epoch = int(epoch)
            self._fence_valid_until = float(valid_until)

    def clear_fence(self) -> None:
        """Disarm the fence (graceful shutdown after lease release)."""
        with self._lock:
            self._fence_epoch = None

    def _exec(self, sql: str, args: tuple = ()) -> None:
        self._chaos(sql)
        with self._lock:
            if (self._fence_epoch is not None
                    and sql.lstrip()[:6].upper() in self._MUTATING_VERBS):
                self._fence_check_locked()
            self._b.execute(sql, args)

    def _one(self, sql: str, args: tuple = ()) -> Optional[Dict[str, Any]]:
        self._chaos(sql)
        with self._lock:
            row = self._b.execute(sql, args).fetchone()
        return self._b.to_dict(row) if row else None

    def _all(self, sql: str, args: tuple = ()) -> List[Dict[str, Any]]:
        self._chaos(sql)
        with self._lock:
            rows = self._b.execute(sql, args).fetchall()
        return [self._b.to_dict(r) for r in rows]

    # -- control-plane leadership lease (docs/failure-model.md) ------------

    @staticmethod
    def _lease_chaos(op: str) -> None:
        """RAFIKI_CHAOS site=lease: deterministic lease faults at the
        acquisition/renewal chokepoint. `delay` models a slow store near
        the TTL edge; `error` (or `drop`) is the false-lease-loss drill —
        the renewal loop must absorb it and the TTL clock (self-fence)
        must decide, never the error itself."""
        rule = chaos.hit(chaos.SITE_LEASE, op)
        if rule is None:
            return
        if rule.action == chaos.ACTION_DELAY:
            chaos.sleep_for(rule)
            return
        raise MetadataStoreChaosError(
            f"chaos-injected lease fault on {op}")

    def acquire_lease(self, holder: str, ttl_s: float,
                      addr: Optional[str] = None) -> Optional[Dict]:
        """Try to take the leadership lease. Succeeds when the row is
        absent, expired, or already ours; EVERY success bumps the
        monotonic epoch (even a re-acquisition by the same holder — its
        own in-flight writes from the previous incarnation must fence).
        Read-check-write runs in one exclusive transaction (same pattern
        as reserve_trial), so two standbys racing an expiry can never
        both win. Returns the new lease dict, or None while a live lease
        is held by someone else."""
        self._lease_chaos("acquire")
        now = time.time()
        with self._lock:
            self._b.begin_exclusive("control_lease")
            try:
                row = self._b.execute(
                    "SELECT * FROM control_lease WHERE id=?", (LEASE_ID,)
                ).fetchone()
                if row is None:
                    epoch = 1
                    self._b.execute(
                        "INSERT INTO control_lease (id, holder, addr, epoch,"
                        " expires_at, datetime_updated) VALUES (?,?,?,?,?,?)",
                        (LEASE_ID, holder, addr, epoch, now + ttl_s, now),
                    )
                elif row["holder"] == holder or row["expires_at"] <= now:
                    epoch = row["epoch"] + 1
                    self._b.execute(
                        "UPDATE control_lease SET holder=?, addr=?, epoch=?,"
                        " expires_at=?, datetime_updated=? WHERE id=?",
                        (holder, addr, epoch, now + ttl_s, now, LEASE_ID),
                    )
                else:
                    self._b.rollback()
                    return None
                self._b.commit()
            except BaseException:
                self._b.rollback()
                raise
        return {"id": LEASE_ID, "holder": holder, "addr": addr,
                "epoch": epoch, "expires_at": now + ttl_s,
                "datetime_updated": now}

    def renew_lease(self, holder: str, epoch: int, ttl_s: float,
                    addr: Optional[str] = None) -> bool:
        """Extend the lease iff (holder, epoch) still match — the CAS that
        makes renewal safe against a standby having promoted meanwhile.
        Expiry alone does NOT fail renewal: if the epoch is unchanged,
        nobody else acquired, so extending is split-brain-safe (the
        holder's own self-fence clock governs whether it kept mutating in
        the gap). False means leadership is gone for good."""
        self._lease_chaos("renew")
        now = time.time()
        with self._lock:
            self._b.begin_exclusive("control_lease")
            try:
                row = self._b.execute(
                    "SELECT * FROM control_lease WHERE id=?", (LEASE_ID,)
                ).fetchone()
                if (row is None or row["holder"] != holder
                        or row["epoch"] != epoch):
                    self._b.rollback()
                    return False
                self._b.execute(
                    "UPDATE control_lease SET addr=?, expires_at=?,"
                    " datetime_updated=? WHERE id=?",
                    (addr if addr is not None else row["addr"],
                     now + ttl_s, now, LEASE_ID),
                )
                self._b.commit()
            except BaseException:
                self._b.rollback()
                raise
        return True

    def release_lease(self, holder: str, epoch: int) -> bool:
        """Graceful handoff: expire the lease NOW (CAS on holder+epoch)
        so a standby can promote without waiting out the TTL. The row —
        and its epoch history — stays."""
        now = time.time()
        with self._lock:
            self._b.begin_exclusive("control_lease")
            try:
                row = self._b.execute(
                    "SELECT * FROM control_lease WHERE id=?", (LEASE_ID,)
                ).fetchone()
                if (row is None or row["holder"] != holder
                        or row["epoch"] != epoch):
                    self._b.rollback()
                    return False
                self._b.execute(
                    "UPDATE control_lease SET expires_at=?,"
                    " datetime_updated=? WHERE id=?",
                    (now, now, LEASE_ID),
                )
                self._b.commit()
            except BaseException:
                self._b.rollback()
                raise
        return True

    def read_lease(self) -> Optional[Dict]:
        """The current lease row (doctor, standby watch, fleet health)."""
        return self._one(
            "SELECT * FROM control_lease WHERE id=?", (LEASE_ID,))

    # -- users -------------------------------------------------------------

    def create_user(self, email: str, password_hash: str, user_type: str) -> Dict:
        uid = uuid.uuid4().hex
        self._exec(
            'INSERT INTO "user" (id, email, password_hash, user_type, banned,'
            " datetime_created) VALUES (?,?,?,?,0,?)",
            (uid, email, password_hash, user_type, time.time()),
        )
        return self.get_user(uid)  # type: ignore[return-value]

    def get_user(self, user_id: str) -> Optional[Dict]:
        return self._one('SELECT * FROM "user" WHERE id=?', (user_id,))

    def get_user_by_email(self, email: str) -> Optional[Dict]:
        return self._one('SELECT * FROM "user" WHERE email=?', (email,))

    def get_users(self) -> List[Dict]:
        return self._all('SELECT * FROM "user" ORDER BY datetime_created')

    def ban_user(self, user_id: str) -> None:
        self._exec('UPDATE "user" SET banned=1 WHERE id=?', (user_id,))

    # -- models ------------------------------------------------------------

    def create_model(
        self,
        user_id: str,
        name: str,
        task: str,
        model_file_bytes: bytes,
        model_class: str,
        dependencies: Dict[str, Optional[str]],
        access_right: str,
        verification: Optional[str] = None,
    ) -> Dict:
        mid = uuid.uuid4().hex
        self._exec(
            "INSERT INTO model (id, user_id, name, task, model_file_bytes,"
            " model_class, dependencies, access_right, verification,"
            " datetime_created)"
            " VALUES (?,?,?,?,?,?,?,?,?,?)",
            (
                mid,
                user_id,
                name,
                task,
                model_file_bytes,
                model_class,
                json.dumps(dependencies),
                access_right,
                verification,
                time.time(),
            ),
        )
        return self.get_model(mid)  # type: ignore[return-value]

    def get_model(self, model_id: str) -> Optional[Dict]:
        m = self._one("SELECT * FROM model WHERE id=?", (model_id,))
        if m:
            m["dependencies"] = json.loads(m["dependencies"])
        return m

    def get_model_by_name(self, user_id: str, name: str) -> Optional[Dict]:
        m = self._one(
            "SELECT * FROM model WHERE user_id=? AND name=?", (user_id, name)
        )
        if m:
            m["dependencies"] = json.loads(m["dependencies"])
        return m

    def get_models(self, task: Optional[str] = None) -> List[Dict]:
        if task:
            rows = self._all("SELECT * FROM model WHERE task=?", (task,))
        else:
            rows = self._all("SELECT * FROM model")
        for m in rows:
            m["dependencies"] = json.loads(m["dependencies"])
        return rows

    def delete_model(self, model_id: str) -> None:
        self._exec("DELETE FROM model WHERE id=?", (model_id,))

    # -- train jobs ----------------------------------------------------------

    def create_train_job(
        self,
        user_id: str,
        app: str,
        app_version: int,
        task: str,
        train_dataset_uri: str,
        test_dataset_uri: str,
        budget: Dict[str, Any],
    ) -> Dict:
        tid = uuid.uuid4().hex
        self._exec(
            "INSERT INTO train_job (id, user_id, app, app_version, task,"
            " train_dataset_uri, test_dataset_uri, budget, status,"
            " datetime_started) VALUES (?,?,?,?,?,?,?,?,?,?)",
            (
                tid,
                user_id,
                app,
                app_version,
                task,
                train_dataset_uri,
                test_dataset_uri,
                json.dumps(budget),
                TrainJobStatus.STARTED,
                time.time(),
            ),
        )
        return self.get_train_job(tid)  # type: ignore[return-value]

    def get_train_job(self, train_job_id: str) -> Optional[Dict]:
        j = self._one("SELECT * FROM train_job WHERE id=?", (train_job_id,))
        if j:
            j["budget"] = json.loads(j["budget"])
        return j

    def get_train_jobs_of_user(self, user_id: str) -> List[Dict]:
        rows = self._all(
            "SELECT * FROM train_job WHERE user_id=?"
            " ORDER BY datetime_started DESC",
            (user_id,),
        )
        for j in rows:
            j["budget"] = json.loads(j["budget"])
        return rows

    def get_train_jobs_of_app(self, user_id: str, app: str) -> List[Dict]:
        rows = self._all(
            "SELECT * FROM train_job WHERE user_id=? AND app=?"
            " ORDER BY app_version DESC",
            (user_id, app),
        )
        for j in rows:
            j["budget"] = json.loads(j["budget"])
        return rows

    def get_train_job_by_app_version(
        self, user_id: str, app: str, app_version: int
    ) -> Optional[Dict]:
        if app_version == -1:
            rows = self.get_train_jobs_of_app(user_id, app)
            return rows[0] if rows else None
        j = self._one(
            "SELECT * FROM train_job WHERE user_id=? AND app=? AND app_version=?",
            (user_id, app, app_version),
        )
        if j:
            j["budget"] = json.loads(j["budget"])
        return j

    def get_next_app_version(self, user_id: str, app: str) -> int:
        row = self._one(
            "SELECT MAX(app_version) AS v FROM train_job WHERE user_id=? AND app=?",
            (user_id, app),
        )
        return (row["v"] or 0) + 1 if row else 1

    # Job status transitions are guarded (WHERE status IN ...) so they are
    # state-machine moves, not blind writes: a fast worker can run a whole
    # job to STOPPED before the deploy path gets around to marking it
    # RUNNING, and that late RUNNING write must lose.

    def mark_train_job_as_running(self, train_job_id: str) -> None:
        self._exec(
            "UPDATE train_job SET status=? WHERE id=? AND status=?",
            (TrainJobStatus.RUNNING, train_job_id, TrainJobStatus.STARTED),
        )

    def mark_train_job_as_stopped(self, train_job_id: str) -> None:
        self._exec(
            "UPDATE train_job SET status=?, datetime_stopped=? WHERE id=?"
            " AND status IN (?,?)",
            (
                TrainJobStatus.STOPPED,
                time.time(),
                train_job_id,
                TrainJobStatus.STARTED,
                TrainJobStatus.RUNNING,
            ),
        )

    def mark_train_job_as_errored(
        self,
        train_job_id: str,
        fault_kind: Optional[str] = None,
        error_reason: Optional[str] = None,
    ) -> None:
        """Error a job with a typed, recorded reason (trial fault
        taxonomy): ``fault_kind`` is the dominant trial fault class that
        killed it (e.g. USER for a poison template failing fast) and
        ``error_reason`` the operator-readable sentence. Both are None
        for legacy callers — the guarded transition is unchanged."""
        self._exec(
            "UPDATE train_job SET status=?, fault_kind=?, error_reason=?,"
            " datetime_stopped=? WHERE id=? AND status IN (?,?)",
            (
                TrainJobStatus.ERRORED,
                fault_kind,
                error_reason,
                time.time(),
                train_job_id,
                TrainJobStatus.STARTED,
                TrainJobStatus.RUNNING,
            ),
        )

    # -- sub train jobs ------------------------------------------------------

    def create_sub_train_job(self, train_job_id: str, model_id: str) -> Dict:
        sid = uuid.uuid4().hex
        self._exec(
            "INSERT INTO sub_train_job (id, train_job_id, model_id) VALUES (?,?,?)",
            (sid, train_job_id, model_id),
        )
        return self.get_sub_train_job(sid)  # type: ignore[return-value]

    def get_sub_train_job(self, sub_train_job_id: str) -> Optional[Dict]:
        return self._one(
            "SELECT * FROM sub_train_job WHERE id=?", (sub_train_job_id,)
        )

    def get_sub_train_jobs_of_train_job(self, train_job_id: str) -> List[Dict]:
        return self._all(
            "SELECT * FROM sub_train_job WHERE train_job_id=?", (train_job_id,)
        )

    def update_sub_train_job_advisor(
        self, sub_train_job_id: str, advisor_id: str
    ) -> None:
        self._exec(
            "UPDATE sub_train_job SET advisor_id=? WHERE id=?",
            (advisor_id, sub_train_job_id),
        )

    # -- workers -------------------------------------------------------------

    def create_train_job_worker(
        self, service_id: str, sub_train_job_id: str
    ) -> Dict:
        self._exec(
            "INSERT INTO train_job_worker (service_id, sub_train_job_id)"
            " VALUES (?,?)",
            (service_id, sub_train_job_id),
        )
        return {"service_id": service_id, "sub_train_job_id": sub_train_job_id}

    def get_train_job_worker(self, service_id: str) -> Optional[Dict]:
        return self._one(
            "SELECT * FROM train_job_worker WHERE service_id=?", (service_id,)
        )

    def get_workers_of_sub_train_job(self, sub_train_job_id: str) -> List[Dict]:
        return self._all(
            "SELECT * FROM train_job_worker WHERE sub_train_job_id=?",
            (sub_train_job_id,),
        )

    def get_workers_of_train_job(self, train_job_id: str) -> List[Dict]:
        return self._all(
            "SELECT w.* FROM train_job_worker w"
            " JOIN sub_train_job s ON w.sub_train_job_id = s.id"
            " WHERE s.train_job_id=?",
            (train_job_id,),
        )

    # -- trials --------------------------------------------------------------

    def create_trial(
        self,
        sub_train_job_id: str,
        model_id: str,
        knobs: Dict[str, Any],
        worker_id: Optional[str] = None,
    ) -> Dict:
        tid = uuid.uuid4().hex
        self._exec(
            "INSERT INTO trial (id, sub_train_job_id, model_id, worker_id,"
            " knobs, status, datetime_started) VALUES (?,?,?,?,?,?,?)",
            (
                tid,
                sub_train_job_id,
                model_id,
                worker_id,
                json.dumps(knobs),
                TrialStatus.RUNNING,
                time.time(),
            ),
        )
        return self.get_trial(tid)  # type: ignore[return-value]

    def reserve_trial(
        self,
        sub_train_job_id: str,
        model_id: str,
        knobs: Dict[str, Any],
        worker_id: Optional[str] = None,
        max_trials: Optional[int] = None,
    ) -> Optional[Dict]:
        """Atomically create a trial iff the sub-train-job's budget allows it.

        Count-then-insert runs in ONE IMMEDIATE transaction, so N parallel
        workers — threads sharing this handle or processes sharing the WAL
        file — can never overshoot ``max_trials`` (the reference's
        check-then-create raced the same way this repo's round-2
        worker/train.py did). Returns the trial row, or None when the budget
        is already spent."""
        tid = uuid.uuid4().hex
        with self._lock:
            # the backend's exclusive transaction (IMMEDIATE write lock on
            # sqlite, advisory xact lock on postgres) guarantees the count
            # below can't be invalidated by another worker between read and
            # insert
            self._b.begin_exclusive(sub_train_job_id)
            try:
                # epoch fence inside the exclusive transaction: the
                # guarded-CAS form — a fenced admin cannot reserve trials
                self._fence_check_locked()
                if max_trials is not None:
                    row = self._b.execute(
                        "SELECT COUNT(*) AS c FROM trial"
                        " WHERE sub_train_job_id=? AND status != ?",
                        (sub_train_job_id, TrialStatus.TERMINATED),
                    ).fetchone()
                    # plain key access is portable: sqlite3.Row and
                    # psycopg2's RealDictRow both support it
                    if row["c"] >= max_trials:
                        self._b.rollback()
                        return None
                self._b.execute(
                    "INSERT INTO trial (id, sub_train_job_id, model_id,"
                    " worker_id, knobs, status, datetime_started)"
                    " VALUES (?,?,?,?,?,?,?)",
                    (
                        tid,
                        sub_train_job_id,
                        model_id,
                        worker_id,
                        json.dumps(knobs),
                        TrialStatus.RUNNING,
                        time.time(),
                    ),
                )
                self._b.commit()
            except BaseException:
                self._b.rollback()
                raise
        return self.get_trial(tid)

    def get_trial(self, trial_id: str) -> Optional[Dict]:
        t = self._one("SELECT * FROM trial WHERE id=?", (trial_id,))
        if t:
            t["knobs"] = json.loads(t["knobs"])
        return t

    def _trials(self, sql: str, args: tuple) -> List[Dict]:
        rows = self._all(sql, args)
        for t in rows:
            t["knobs"] = json.loads(t["knobs"])
        return rows

    def get_trials_of_sub_train_job(self, sub_train_job_id: str) -> List[Dict]:
        return self._trials(
            "SELECT * FROM trial WHERE sub_train_job_id=?"
            " ORDER BY datetime_started",
            (sub_train_job_id,),
        )

    def get_trials_of_train_job(self, train_job_id: str) -> List[Dict]:
        return self._trials(
            "SELECT t.* FROM trial t"
            " JOIN sub_train_job s ON t.sub_train_job_id = s.id"
            " WHERE s.train_job_id=? ORDER BY t.datetime_started",
            (train_job_id,),
        )

    def get_best_trials_of_train_job(
        self, train_job_id: str, max_count: int = 2
    ) -> List[Dict]:
        """Completed trials ordered by score desc (reference
        rafiki/db/database.py:425-433)."""
        return self._trials(
            "SELECT t.* FROM trial t"
            " JOIN sub_train_job s ON t.sub_train_job_id = s.id"
            " WHERE s.train_job_id=? AND t.status=?"
            " ORDER BY t.score DESC LIMIT ?",
            (train_job_id, TrialStatus.COMPLETED, max_count),
        )

    def count_trials_of_sub_train_job(self, sub_train_job_id: str) -> int:
        """All non-terminated trials count toward budget (the reference also
        counted errored trials, reference worker/train.py:231)."""
        row = self._one(
            "SELECT COUNT(*) AS c FROM trial WHERE sub_train_job_id=?"
            " AND status != ?",
            (sub_train_job_id, TrialStatus.TERMINATED),
        )
        return row["c"] if row else 0

    def mark_trial_as_complete(
        self, trial_id: str, score: float, params_file_path: Optional[str]
    ) -> None:
        self._exec(
            "UPDATE trial SET status=?, score=?, params_file_path=?,"
            " datetime_stopped=? WHERE id=?",
            (TrialStatus.COMPLETED, score, params_file_path, time.time(), trial_id),
        )

    def mark_trial_as_errored(
        self,
        trial_id: str,
        fault_kind: Optional[str] = None,
        fault_detail: Optional[str] = None,
    ) -> None:
        """Terminal failure with its taxonomy kind and truncated
        traceback recorded on the row — diagnosing a failed trial must
        not require scraping worker logs (worker/faults.py)."""
        self._exec(
            "UPDATE trial SET status=?, fault_kind=?, fault_detail=?,"
            " datetime_stopped=? WHERE id=?",
            (TrialStatus.ERRORED, fault_kind, fault_detail, time.time(),
             trial_id),
        )

    def record_trial_fault(
        self, trial_id: str, fault_kind: str, fault_detail: Optional[str]
    ) -> int:
        """An infra-class fault the worker is about to RETRY: bump the
        attempt counter and record the latest fault kind/detail, but
        keep the trial RUNNING (same id, same knobs, same budget slot).
        Returns the new attempt number."""
        self._exec(
            "UPDATE trial SET attempt=attempt+1, fault_kind=?,"
            " fault_detail=? WHERE id=?",
            (fault_kind, fault_detail, trial_id),
        )
        row = self._one("SELECT attempt FROM trial WHERE id=?", (trial_id,))
        return int(row["attempt"]) if row else 0

    def get_trial_fault_counts_of_train_job(
        self, train_job_id: str
    ) -> Dict[str, int]:
        """fault_kind -> count across the job's ERRORED trials (doctor).
        Only terminal failures count as faults here — COMPLETED/RUNNING
        rows keep the kind of a transient fault they absorbed for
        per-trial observability, but a healthy job must not read as
        faulted in aggregate (its absorbed re-runs show as retries)."""
        rows = self._all(
            "SELECT t.fault_kind AS k, COUNT(*) AS c FROM trial t"
            " JOIN sub_train_job s ON t.sub_train_job_id = s.id"
            " WHERE s.train_job_id=? AND t.fault_kind IS NOT NULL"
            " AND t.status=?"
            " GROUP BY t.fault_kind",
            (train_job_id, TrialStatus.ERRORED),
        )
        return {r["k"]: int(r["c"]) for r in rows}

    def get_trial_fault_summary_of_live_jobs(self) -> Dict[str, Dict]:
        """One grouped query for the fleet-health "training" section:
        train_job_id -> {"faults": {kind: count}, "retries": total}
        across every STARTED/RUNNING train job — never a per-job query
        fan-out inside the health handler. ``faults`` counts only
        ERRORED rows (terminal failures); absorbed transient re-runs —
        on any row, whatever its current status — aggregate into
        ``retries``."""
        rows = self._all(
            "SELECT s.train_job_id AS jid, t.fault_kind AS k,"
            " t.status AS st, COUNT(*) AS c,"
            " COALESCE(SUM(t.attempt), 0) AS a"
            " FROM trial t"
            " JOIN sub_train_job s ON t.sub_train_job_id = s.id"
            " JOIN train_job j ON s.train_job_id = j.id"
            " WHERE j.status IN (?,?)"
            " GROUP BY s.train_job_id, t.fault_kind, t.status",
            (TrainJobStatus.STARTED, TrainJobStatus.RUNNING),
        )
        out: Dict[str, Dict] = {}
        for r in rows:
            entry = out.setdefault(r["jid"], {"faults": {}, "retries": 0})
            if r["k"] is not None and r["st"] == TrialStatus.ERRORED:
                entry["faults"][r["k"]] = \
                    entry["faults"].get(r["k"], 0) + int(r["c"])
            entry["retries"] += int(r["a"])
        return out


    def mark_trial_as_terminated(self, trial_id: str) -> None:
        self._exec(
            "UPDATE trial SET status=?, datetime_stopped=? WHERE id=?",
            (TrialStatus.TERMINATED, time.time(), trial_id),
        )

    def add_trial_log(self, trial_id: str, line: str) -> None:
        self._exec(
            "INSERT INTO trial_log (trial_id, line, datetime) VALUES (?,?,?)",
            (trial_id, line, time.time()),
        )

    def get_trial_logs(self, trial_id: str) -> List[str]:
        return [
            r["line"]
            for r in self._all(
                "SELECT line FROM trial_log WHERE trial_id=? ORDER BY id",
                (trial_id,),
            )
        ]

    # -- inference jobs ------------------------------------------------------

    def create_inference_job(self, user_id: str, train_job_id: str,
                             budget: Optional[Dict[str, Any]] = None) -> Dict:
        iid = uuid.uuid4().hex
        self._exec(
            "INSERT INTO inference_job (id, user_id, train_job_id, status,"
            " budget, datetime_started) VALUES (?,?,?,?,?,?)",
            (iid, user_id, train_job_id, InferenceJobStatus.STARTED,
             json.dumps(budget or {}), time.time()),
        )
        return self.get_inference_job(iid)  # type: ignore[return-value]

    @staticmethod
    def _parse_inference_budget(row: Optional[Dict]) -> Optional[Dict]:
        # NULL budget: row predates the r5 migration — treat as empty
        if row is not None:
            row["budget"] = json.loads(row["budget"] or "{}")
        return row

    def get_inference_job(self, inference_job_id: str) -> Optional[Dict]:
        return self._parse_inference_budget(self._one(
            "SELECT * FROM inference_job WHERE id=?", (inference_job_id,)
        ))

    def get_inference_jobs_of_train_job(self, train_job_id: str) -> List[Dict]:
        rows = self._all(
            "SELECT * FROM inference_job WHERE train_job_id=?"
            " ORDER BY datetime_started DESC",
            (train_job_id,),
        )
        return [self._parse_inference_budget(r) for r in rows]

    def get_inference_jobs_by_statuses(self, statuses: List[str]) -> List[Dict]:
        marks = ",".join("?" * len(statuses))
        rows = self._all(
            f"SELECT * FROM inference_job WHERE status IN ({marks})",
            tuple(statuses),
        )
        return [self._parse_inference_budget(r) for r in rows]

    def get_train_jobs_by_statuses(self, statuses: List[str]) -> List[Dict]:
        marks = ",".join("?" * len(statuses))
        rows = self._all(
            f"SELECT * FROM train_job WHERE status IN ({marks})", tuple(statuses)
        )
        for j in rows:
            j["budget"] = json.loads(j["budget"])
        return rows

    def get_running_inference_job_of_train_job(
        self, train_job_id: str
    ) -> Optional[Dict]:
        return self._parse_inference_budget(self._one(
            "SELECT * FROM inference_job WHERE train_job_id=? AND status IN (?,?)",
            (train_job_id, InferenceJobStatus.STARTED, InferenceJobStatus.RUNNING),
        ))

    def update_inference_job_predictor(
        self, inference_job_id: str, predictor_service_id: str
    ) -> None:
        self._exec(
            "UPDATE inference_job SET predictor_service_id=? WHERE id=?",
            (predictor_service_id, inference_job_id),
        )

    def mark_inference_job_as_running(self, inference_job_id: str) -> None:
        self._exec(
            "UPDATE inference_job SET status=? WHERE id=? AND status=?",
            (InferenceJobStatus.RUNNING, inference_job_id, InferenceJobStatus.STARTED),
        )

    def mark_inference_job_as_stopped(self, inference_job_id: str) -> None:
        self._exec(
            "UPDATE inference_job SET status=?, datetime_stopped=? WHERE id=?"
            " AND status IN (?,?)",
            (
                InferenceJobStatus.STOPPED,
                time.time(),
                inference_job_id,
                InferenceJobStatus.STARTED,
                InferenceJobStatus.RUNNING,
            ),
        )

    def mark_inference_job_as_errored(self, inference_job_id: str) -> None:
        self._exec(
            "UPDATE inference_job SET status=?, datetime_stopped=? WHERE id=?"
            " AND status IN (?,?)",
            (
                InferenceJobStatus.ERRORED,
                time.time(),
                inference_job_id,
                InferenceJobStatus.STARTED,
                InferenceJobStatus.RUNNING,
            ),
        )

    def create_inference_job_worker(
        self, service_id: str, inference_job_id: str, trial_id: str,
        model_version: int = 0, standby: bool = False,
    ) -> Dict:
        """``model_version`` is the rollout generation this replica
        serves (0 for the initial deploy; admin/rollout.py bumps it per
        in-place update) — recovery reads it to reconstruct a
        mixed-version fleet mid-rollout. ``standby`` marks a warm-pool
        replica: loaded and warmed but NOT routed until promotion
        (admin/warm_pool.py) — recovery keeps standbys out of the
        predictor's routable set when it adopts a fleet."""
        self._exec(
            "INSERT INTO inference_job_worker (service_id, inference_job_id,"
            " trial_id, model_version, standby) VALUES (?,?,?,?,?)",
            (service_id, inference_job_id, trial_id, int(model_version),
             1 if standby else 0),
        )
        return {
            "service_id": service_id,
            "inference_job_id": inference_job_id,
            "trial_id": trial_id,
            "model_version": int(model_version),
            "standby": 1 if standby else 0,
        }

    def get_inference_job_worker(self, service_id: str) -> Optional[Dict]:
        return self._one(
            "SELECT * FROM inference_job_worker WHERE service_id=?", (service_id,)
        )

    def set_worker_borrowed_chips(self, service_id: str, n_chips: int) -> None:
        """Persist how many chips this serving replica borrowed from the
        training floor (0 = none). The ChipBudgetArbiter's loan book is
        in-memory; this marker is what lets a restarted admin rebuild it
        for adopted replicas instead of leaking the loan
        (admin/recovery.py)."""
        self._exec(
            "UPDATE inference_job_worker SET borrowed_chips=?"
            " WHERE service_id=?",
            (int(n_chips), service_id),
        )

    def set_worker_standby(self, service_id: str, standby: bool) -> None:
        """Flip a replica's warm-standby marker (0 = routable). Promotion
        clears it BEFORE predictor add_worker, so a crash between the two
        leaves a promotable-but-unrouted replica (re-promoted or swept),
        never a routed row recovery would mistake for a standby."""
        self._exec(
            "UPDATE inference_job_worker SET standby=? WHERE service_id=?",
            (1 if standby else 0, service_id),
        )

    def get_workers_of_inference_job(self, inference_job_id: str) -> List[Dict]:
        return self._all(
            "SELECT * FROM inference_job_worker WHERE inference_job_id=?",
            (inference_job_id,),
        )

    # -- rollouts (admin/rollout.py; docs/failure-model.md
    # "Rollout faults") ------------------------------------------------------

    @staticmethod
    def _parse_rollout(row: Optional[Dict]) -> Optional[Dict]:
        if row is not None:
            try:
                row["events"] = json.loads(row.get("events") or "[]")
            except ValueError:
                row["events"] = []
            row["operator_ack"] = bool(row.get("operator_ack"))
        return row

    def create_rollout(
        self, inference_job_id: str, from_trial_id: Optional[str],
        to_trial_id: str, from_version: int, to_version: int,
        n_replicas_before: int, phase: str,
    ) -> Dict:
        rid = uuid.uuid4().hex
        self._exec(
            "INSERT INTO rollout (id, inference_job_id, from_trial_id,"
            " to_trial_id, from_version, to_version, n_replicas_before,"
            " phase, datetime_started) VALUES (?,?,?,?,?,?,?,?,?)",
            (rid, inference_job_id, from_trial_id, to_trial_id,
             int(from_version), int(to_version), int(n_replicas_before),
             phase, time.time()),
        )
        return self.get_rollout(rid)  # type: ignore[return-value]

    def get_rollout(self, rollout_id: str) -> Optional[Dict]:
        return self._parse_rollout(self._one(
            "SELECT * FROM rollout WHERE id=?", (rollout_id,)))

    def get_rollouts_of_inference_job(
        self, inference_job_id: str
    ) -> List[Dict]:
        rows = self._all(
            "SELECT * FROM rollout WHERE inference_job_id=?"
            " ORDER BY datetime_started DESC",
            (inference_job_id,),
        )
        return [self._parse_rollout(r) for r in rows]

    def get_rollouts_by_phases(self, phases: List[str]) -> List[Dict]:
        """Rollout rows in the given phases — recovery scans the LIVE
        phases (a half-finished rollout must be resumed or rolled back,
        never stranded) and doctor the unacked ROLLED_BACK ones."""
        marks = ",".join("?" * len(phases))
        rows = self._all(
            f"SELECT * FROM rollout WHERE phase IN ({marks})",
            tuple(phases),
        )
        return [self._parse_rollout(r) for r in rows]

    def mark_rollout_phase(
        self, rollout_id: str, phase: str, reason: Optional[str] = None,
    ) -> None:
        """Phase transition; terminal phases stamp datetime_stopped and
        record the reason (rollback trigger / abort cause)."""
        if phase in RolloutPhase.TERMINAL:
            self._exec(
                "UPDATE rollout SET phase=?, reason=?, datetime_stopped=?"
                " WHERE id=?",
                (phase, reason, time.time(), rollout_id),
            )
        else:
            self._exec(
                "UPDATE rollout SET phase=? WHERE id=?", (phase, rollout_id))

    def update_rollout_events(self, rollout_id: str, events: List[Dict]) -> None:
        self._exec(
            "UPDATE rollout SET events=? WHERE id=?",
            (json.dumps(events), rollout_id),
        )

    def ack_rollout(self, rollout_id: str) -> None:
        """Operator acknowledgment of a rollback (doctor WARNs on
        ROLLED_BACK rollouts nobody has looked at)."""
        self._exec(
            "UPDATE rollout SET operator_ack=1 WHERE id=?", (rollout_id,))

    # -- drift loop state (admin/drift.py; docs/failure-model.md
    # "Model drift faults") --------------------------------------------------

    @staticmethod
    def _parse_drift_state(row: Optional[Dict]) -> Optional[Dict]:
        if row is not None:
            for key in ("baseline", "signals"):
                try:
                    row[key] = (json.loads(row[key])
                                if row.get(key) else None)
                except ValueError:
                    row[key] = None
            try:
                row["events"] = json.loads(row.get("events") or "[]")
            except ValueError:
                row["events"] = []
            row["operator_ack"] = bool(row.get("operator_ack"))
        return row

    def create_drift_state(self, inference_job_id: str, phase: str) -> Dict:
        self._exec(
            "INSERT INTO drift_state (inference_job_id, phase,"
            " datetime_updated) VALUES (?,?,?)",
            (inference_job_id, phase, time.time()),
        )
        return self.get_drift_state(  # type: ignore[return-value]
            inference_job_id)

    def get_drift_state(self, inference_job_id: str) -> Optional[Dict]:
        return self._parse_drift_state(self._one(
            "SELECT * FROM drift_state WHERE inference_job_id=?",
            (inference_job_id,)))

    def get_drift_states(self) -> List[Dict]:
        """Every drift row — recovery resumes the LIVE phases
        (RETRAINING/ROLLING_OUT must never double-launch or strand a
        candidate) and doctor scans for flap/parked signals."""
        rows = self._all("SELECT * FROM drift_state")
        return [self._parse_drift_state(r) for r in rows]

    def update_drift_state(self, inference_job_id: str, **fields) -> None:
        """Write-through for the drift loop's mutable row. JSON-typed
        fields (baseline/signals/events) are encoded here; pass an
        explicit None to null baseline/signals out (refreeze)."""
        allowed = ("phase", "reason", "baseline", "signals",
                   "retrain_job_id", "candidate_trial_id",
                   "cooldown_until", "consecutive_rollbacks", "events",
                   "operator_ack")
        unknown = set(fields) - set(allowed)
        if unknown:
            raise ValueError(f"unknown drift_state fields {sorted(unknown)}")
        sets, vals = [], []
        for key in allowed:
            if key not in fields:
                continue
            val = fields[key]
            if key in ("baseline", "signals"):
                val = json.dumps(val) if val is not None else None
            elif key == "events":
                val = json.dumps(val or [])
            elif key == "operator_ack":
                val = 1 if val else 0
            sets.append(f"{key}=?")
            vals.append(val)
        sets.append("datetime_updated=?")
        vals.append(time.time())
        vals.append(inference_job_id)
        self._exec(
            "UPDATE drift_state SET " + ", ".join(sets)
            + " WHERE inference_job_id=?",
            tuple(vals),
        )

    # -- services ------------------------------------------------------------

    def create_service(
        self, service_type: str, replicas: int = 1, chips: Optional[List[int]] = None
    ) -> Dict:
        sid = uuid.uuid4().hex
        self._exec(
            "INSERT INTO service (id, service_type, status, replicas, chips,"
            " datetime_started) VALUES (?,?,?,?,?,?)",
            (
                sid,
                service_type,
                ServiceStatus.STARTED,
                replicas,
                json.dumps(chips or []),
                time.time(),
            ),
        )
        return self.get_service(sid)  # type: ignore[return-value]

    def get_service(self, service_id: str) -> Optional[Dict]:
        s = self._one("SELECT * FROM service WHERE id=?", (service_id,))
        if s:
            s["chips"] = json.loads(s["chips"])
        return s

    def get_services(self, status: Optional[str] = None,
                     statuses: Optional[List[str]] = None) -> List[Dict]:
        """Services, optionally filtered by one ``status`` or a
        ``statuses`` list — the filter runs in SQL (against
        idx_service_status), not as an O(N) python sweep at call sites."""
        if statuses:
            marks = ",".join("?" * len(statuses))
            rows = self._all(
                f"SELECT * FROM service WHERE status IN ({marks})",
                tuple(statuses))
        elif status:
            rows = self._all("SELECT * FROM service WHERE status=?", (status,))
        else:
            rows = self._all("SELECT * FROM service")
        for s in rows:
            s["chips"] = json.loads(s["chips"])
        return rows

    def get_non_terminal_services(self) -> List[Dict]:
        """The control-plane recovery scan, as ONE query: every service
        row not yet terminal, joined to its job linkage — train worker
        (sub_train_job_id / train_job_id / train_job_status), inference
        worker (inference_job_id / trial_id / inference_job_status), and
        predictor head (predictor_job_id / predictor_job_status) — so a
        restarted admin never does per-service round trips while deciding
        adopt vs reschedule vs fence (docs/failure-model.md)."""
        live = (ServiceStatus.STARTED, ServiceStatus.DEPLOYING,
                ServiceStatus.RUNNING)
        marks = ",".join("?" * len(live))
        rows = self._all(
            "SELECT s.*,"
            " tw.sub_train_job_id AS sub_train_job_id,"
            " st.train_job_id AS train_job_id,"
            " tj.status AS train_job_status,"
            " iw.inference_job_id AS inference_job_id,"
            " iw.trial_id AS trial_id,"
            " iw.model_version AS model_version,"
            " iw.borrowed_chips AS borrowed_chips,"
            " ij.status AS inference_job_status,"
            " pj.id AS predictor_job_id,"
            " pj.status AS predictor_job_status"
            " FROM service s"
            " LEFT JOIN train_job_worker tw ON tw.service_id = s.id"
            " LEFT JOIN sub_train_job st ON st.id = tw.sub_train_job_id"
            " LEFT JOIN train_job tj ON tj.id = st.train_job_id"
            " LEFT JOIN inference_job_worker iw ON iw.service_id = s.id"
            " LEFT JOIN inference_job ij ON ij.id = iw.inference_job_id"
            " LEFT JOIN inference_job pj ON pj.predictor_service_id = s.id"
            f" WHERE s.status IN ({marks})",
            live,
        )
        for s in rows:
            s["chips"] = json.loads(s["chips"])
        return rows

    def update_service_pid(self, service_id: str,
                           pid: Optional[int]) -> None:
        """Record the worker process backing a service (process
        placement), so a restarted control plane can adopt — or fence — a
        child that survived it."""
        self._exec(
            "UPDATE service SET pid=? WHERE id=?", (pid, service_id))

    def update_service_chips(self, service_id: str, chips: List[int]) -> None:
        self._exec(
            "UPDATE service SET chips=? WHERE id=?",
            (json.dumps(list(chips)), service_id),
        )

    def update_service_host_port(
        self, service_id: str, host: str, port: int
    ) -> None:
        self._exec(
            "UPDATE service SET host=?, port=? WHERE id=?", (host, port, service_id)
        )

    def mark_service_as_deploying(self, service_id: str) -> None:
        """Guarded STARTED -> DEPLOYING: a fast worker may already have
        reported RUNNING (or even finished) by the time the deploy path
        gets here, and that later status must win. Doctor's "rollouts"
        check flags rows stuck in DEPLOYING past the deploy timeout —
        the signature of a wedged placement."""
        self._exec(
            "UPDATE service SET status=? WHERE id=? AND status=?",
            (ServiceStatus.DEPLOYING, service_id, ServiceStatus.STARTED),
        )

    def mark_service_as_running(self, service_id: str) -> None:
        self._exec(
            "UPDATE service SET status=? WHERE id=?",
            (ServiceStatus.RUNNING, service_id),
        )

    def mark_service_as_stopped(self, service_id: str) -> None:
        self._exec(
            "UPDATE service SET status=?, datetime_stopped=? WHERE id=?",
            (ServiceStatus.STOPPED, time.time(), service_id),
        )

    def mark_service_as_errored(self, service_id: str) -> None:
        self._exec(
            "UPDATE service SET status=?, datetime_stopped=? WHERE id=?",
            (ServiceStatus.ERRORED, time.time(), service_id),
        )
