"""Metadata store (L2): job/trial/model/service state
(reference rafiki/db/, SURVEY.md §2.7)."""

from rafiki_tpu.db.database import Database  # noqa: F401
