"""On-demand native build: compile src/*.cpp into a cached shared library.

No pybind11 in this environment, so bindings are plain `extern "C"` + ctypes
(see shm_queue.py). The library is built once per source-hash into
~/.cache/rafiki_tpu (or RAFIKI_NATIVE_CACHE) and memoized; if no compiler is
available the callers fall back to pure-Python implementations, so the
framework never *requires* the native path — it's the fast path.
"""

from __future__ import annotations

import ctypes
import hashlib
import logging
import os
import subprocess
import tempfile
import threading
from typing import Optional

logger = logging.getLogger(__name__)

_SRC_DIR = os.path.join(os.path.dirname(os.path.abspath(__file__)), "src")

_lock = threading.Lock()
_cache: dict = {}


def _cache_dir() -> str:
    return os.environ.get(
        "RAFIKI_NATIVE_CACHE",
        os.path.join(os.path.expanduser("~"), ".cache", "rafiki_tpu"),
    )


def _source_path(name: str) -> str:
    return os.path.join(_SRC_DIR, f"{name}.cpp")


def build_library(name: str) -> Optional[str]:
    """Compile src/<name>.cpp -> cached .so; returns the path or None."""
    src = _source_path(name)
    if not os.path.exists(src):
        logger.error("no native source %s", src)
        return None
    with open(src, "rb") as f:
        digest = hashlib.sha256(f.read()).hexdigest()[:16]
    out_dir = _cache_dir()
    out = os.path.join(out_dir, f"lib{name}-{digest}.so")
    if os.path.exists(out):
        return out
    os.makedirs(out_dir, exist_ok=True)
    cmd = [
        "g++", "-std=c++17", "-O2", "-shared", "-fPIC",
        src, "-o", out + ".tmp", "-lpthread", "-lrt",
    ]
    try:
        subprocess.run(cmd, check=True, capture_output=True, timeout=120)
    except FileNotFoundError:
        logger.warning("g++ not available; native %s disabled", name)
        return None
    except subprocess.CalledProcessError as e:
        logger.error("native build of %s failed:\n%s", name,
                     e.stderr.decode(errors="replace"))
        return None
    os.replace(out + ".tmp", out)
    return out


def load_library(name: str) -> Optional[ctypes.CDLL]:
    """Build (if needed) and dlopen a native library; memoized; None if the
    toolchain is unavailable."""
    with _lock:
        if name in _cache:
            return _cache[name]
        path = build_library(name)
        lib = None
        if path is not None:
            try:
                lib = ctypes.CDLL(path)
            except OSError:
                logger.exception("failed to load %s", path)
        _cache[name] = lib
        return lib
