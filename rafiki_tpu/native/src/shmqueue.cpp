// Shared-memory message queue — the native transport of the serving data
// plane.
//
// The reference's predictor <-> inference-worker transport was a Redis
// server (C) polled over TCP with 0.25 s sleeps on both sides (reference
// rafiki/cache/cache.py:36-78, predictor/predictor.py:46-59). This is the
// TPU-host-native replacement: a POSIX shm ring buffer of length-prefixed
// messages with a process-shared mutex + condvars, so co-located predictor
// and worker *processes* hand off queries in microseconds with no broker
// server, no TCP, and no polling. The Python side binds via ctypes
// (rafiki_tpu/native/shm_queue.py); a pure-Python in-process broker remains
// the fallback when no compiler is available.
//
// Concurrency: MPMC. One mutex guards head/tail; not_empty/not_full condvars
// wake blocked readers/writers. Robustness: PTHREAD_MUTEX_ROBUST so a
// crashed holder doesn't deadlock survivors (EOWNERDEAD is recovered).
//
// Layout in the shm segment:
//   [Header][data ring of capacity bytes]
// Messages are [u32 length][payload], contiguous; a write that would
// straddle the end writes a u32 0xFFFFFFFF wrap marker (if >= 4 bytes
// remain) and restarts at offset 0.

#include <atomic>
#include <cerrno>
#include <cstdint>
#include <cstring>
#include <ctime>

#include <fcntl.h>
#include <pthread.h>
#include <sys/mman.h>
#include <sys/stat.h>
#include <unistd.h>

namespace {

constexpr uint32_t kMagic = 0x52465451;  // "RFTQ"
constexpr uint32_t kWrapMarker = 0xFFFFFFFFu;

struct Header {
  uint32_t magic;
  uint32_t capacity;      // bytes in the data ring
  uint64_t head;          // read offset  (monotonic, mod capacity)
  uint64_t tail;          // write offset (monotonic, mod capacity)
  uint64_t used;          // bytes currently in the ring
  uint32_t closed;
  pthread_mutex_t mutex;
  pthread_cond_t not_empty;
  pthread_cond_t not_full;
};

struct Handle {
  Header* hdr;
  uint8_t* data;
  size_t map_size;
  int owner;  // created (vs opened): unlink responsibility
  char name[256];
};

void timeout_to_abs(long timeout_ms, timespec* ts) {
  clock_gettime(CLOCK_REALTIME, ts);
  ts->tv_sec += timeout_ms / 1000;
  ts->tv_nsec += (timeout_ms % 1000) * 1000000L;
  if (ts->tv_nsec >= 1000000000L) {
    ts->tv_sec += 1;
    ts->tv_nsec -= 1000000000L;
  }
}

// Lock, recovering from a crashed previous owner.
int robust_lock(pthread_mutex_t* m) {
  int rc = pthread_mutex_lock(m);
  if (rc == EOWNERDEAD) {
    pthread_mutex_consistent(m);
    rc = 0;
  }
  return rc;
}

int robust_timedlock(pthread_mutex_t* m, const timespec* ts) {
  int rc = pthread_mutex_timedlock(m, ts);
  if (rc == EOWNERDEAD) {
    pthread_mutex_consistent(m);
    rc = 0;
  }
  return rc;
}

}  // namespace

extern "C" {

// Create (owner=1) or open (owner=0) a queue. Returns nullptr on error.
void* shmq_create(const char* name, uint32_t capacity) {
  size_t map_size = sizeof(Header) + capacity;
  int fd = shm_open(name, O_CREAT | O_EXCL | O_RDWR, 0600);
  if (fd < 0) return nullptr;
  if (ftruncate(fd, (off_t)map_size) != 0) {
    close(fd);
    shm_unlink(name);
    return nullptr;
  }
  void* mem =
      mmap(nullptr, map_size, PROT_READ | PROT_WRITE, MAP_SHARED, fd, 0);
  close(fd);
  if (mem == MAP_FAILED) {
    shm_unlink(name);
    return nullptr;
  }
  Header* hdr = (Header*)mem;
  std::memset(hdr, 0, sizeof(Header));
  hdr->capacity = capacity;

  pthread_mutexattr_t ma;
  pthread_mutexattr_init(&ma);
  pthread_mutexattr_setpshared(&ma, PTHREAD_PROCESS_SHARED);
  pthread_mutexattr_setrobust(&ma, PTHREAD_MUTEX_ROBUST);
  pthread_mutex_init(&hdr->mutex, &ma);
  pthread_mutexattr_destroy(&ma);

  pthread_condattr_t ca;
  pthread_condattr_init(&ca);
  pthread_condattr_setpshared(&ca, PTHREAD_PROCESS_SHARED);
  pthread_cond_init(&hdr->not_empty, &ca);
  pthread_cond_init(&hdr->not_full, &ca);
  pthread_condattr_destroy(&ca);

  hdr->magic = kMagic;  // last: marks fully-initialized

  Handle* h = new Handle;
  h->hdr = hdr;
  h->data = (uint8_t*)mem + sizeof(Header);
  h->map_size = map_size;
  h->owner = 1;
  std::strncpy(h->name, name, sizeof(h->name) - 1);
  h->name[sizeof(h->name) - 1] = 0;
  return h;
}

void* shmq_open(const char* name) {
  int fd = shm_open(name, O_RDWR, 0600);
  if (fd < 0) return nullptr;
  struct stat st;
  if (fstat(fd, &st) != 0 || (size_t)st.st_size < sizeof(Header)) {
    close(fd);
    return nullptr;
  }
  void* mem = mmap(nullptr, (size_t)st.st_size, PROT_READ | PROT_WRITE,
                   MAP_SHARED, fd, 0);
  close(fd);
  if (mem == MAP_FAILED) return nullptr;
  Header* hdr = (Header*)mem;
  if (hdr->magic != kMagic) {
    munmap(mem, (size_t)st.st_size);
    return nullptr;
  }
  Handle* h = new Handle;
  h->hdr = hdr;
  h->data = (uint8_t*)mem + sizeof(Header);
  h->map_size = (size_t)st.st_size;
  h->owner = 0;
  std::strncpy(h->name, name, sizeof(h->name) - 1);
  h->name[sizeof(h->name) - 1] = 0;
  return h;
}

// Push one message. Returns 0 ok, -1 timeout, -2 closed, -3 too large.
int shmq_push(void* hv, const uint8_t* buf, uint32_t len, long timeout_ms) {
  Handle* h = (Handle*)hv;
  Header* q = h->hdr;
  if (4ull + len > q->capacity) return -3;  // unfittable even when empty
  timespec ts;
  timeout_to_abs(timeout_ms, &ts);
  // timed, so a stopped (e.g. SIGSTOP'd) lock holder can't block a push
  // past its deadline — mirrors shmq_pop
  if (robust_timedlock(&q->mutex, &ts) != 0) return -1;
  // The space requirement depends on where tail sits (a wrap skips the
  // remainder of the ring), and tail moves whenever another producer gets
  // in between our waits — so recompute it every iteration.
  uint32_t cap = q->capacity;
  for (;;) {
    if (q->closed) {
      pthread_mutex_unlock(&q->mutex);
      return -2;
    }
    uint64_t tail = q->tail % cap;
    uint64_t room_to_end = cap - tail;
    uint64_t required = 4ull + len;
    if (room_to_end < required) required += room_to_end;  // wrap skip bytes
    if (cap - q->used >= required) break;
    if (q->used == 0) {
      // ring empty (head == tail, nothing in flight) yet insufficient:
      // only the wrap-skip remainder is in the way. Rebase both cursors
      // to 0 — any message that fits an empty ring now fits (the entry
      // check guarantees 4+len <= capacity), so the recompute breaks.
      q->head = 0;
      q->tail = 0;
      continue;
    }
    int rc = pthread_cond_timedwait(&q->not_full, &q->mutex, &ts);
    if (rc == ETIMEDOUT) {
      pthread_mutex_unlock(&q->mutex);
      return -1;
    }
    if (rc == EOWNERDEAD) pthread_mutex_consistent(&q->mutex);
  }
  uint64_t tail = q->tail % cap;
  uint64_t room_to_end = cap - tail;
  if (room_to_end < 4 + (uint64_t)len) {
    // not enough contiguous room: lay a wrap marker (if >= 4 bytes) and
    // restart at 0. `used` accounts the skipped bytes.
    if (room_to_end >= 4) {
      uint32_t marker = kWrapMarker;
      std::memcpy(h->data + tail, &marker, 4);
    }
    q->tail += room_to_end;
    q->used += room_to_end;
    tail = 0;
  }
  std::memcpy(h->data + tail, &len, 4);
  std::memcpy(h->data + tail + 4, buf, len);
  q->tail += 4 + len;
  q->used += 4 + len;
  pthread_cond_signal(&q->not_empty);
  pthread_mutex_unlock(&q->mutex);
  return 0;
}

// Pop one message into buf. Returns payload length (>=0), -1 timeout,
// -2 closed-and-empty, -4 buffer too small (message left in place; required
// size written into *required_out if non-null).
int shmq_pop(void* hv, uint8_t* buf, uint32_t buflen, long timeout_ms,
             uint32_t* required_out) {
  Handle* h = (Handle*)hv;
  Header* q = h->hdr;
  timespec ts;
  timeout_to_abs(timeout_ms, &ts);
  if (robust_timedlock(&q->mutex, &ts) != 0) return -1;
  while (q->used == 0 && !q->closed) {
    int rc = pthread_cond_timedwait(&q->not_empty, &q->mutex, &ts);
    if (rc == ETIMEDOUT) {
      pthread_mutex_unlock(&q->mutex);
      return -1;
    }
    if (rc == EOWNERDEAD) pthread_mutex_consistent(&q->mutex);
  }
  if (q->used == 0 && q->closed) {
    pthread_mutex_unlock(&q->mutex);
    return -2;
  }
  uint32_t cap = q->capacity;
  uint64_t head = q->head % cap;
  uint64_t room_to_end = cap - head;
  uint32_t len;
  if (room_to_end < 4) {
    // writer wrapped without room for a marker
    q->head += room_to_end;
    q->used -= room_to_end;
    head = 0;
  } else {
    std::memcpy(&len, h->data + head, 4);
    if (len == kWrapMarker) {
      q->head += room_to_end;
      q->used -= room_to_end;
      head = 0;
    }
  }
  std::memcpy(&len, h->data + head, 4);
  if (len > buflen) {
    if (required_out) *required_out = len;
    pthread_mutex_unlock(&q->mutex);
    return -4;
  }
  std::memcpy(buf, h->data + head + 4, len);
  q->head += 4 + len;
  q->used -= 4 + len;
  pthread_cond_signal(&q->not_full);
  pthread_mutex_unlock(&q->mutex);
  return (int)len;
}

// Number of queued bytes (diagnostics).
uint64_t shmq_used(void* hv) {
  Handle* h = (Handle*)hv;
  if (robust_lock(&h->hdr->mutex) != 0) return 0;
  uint64_t u = h->hdr->used;
  pthread_mutex_unlock(&h->hdr->mutex);
  return u;
}

// Mark closed: pending/future pops drain then return -2; pushes return -2.
void shmq_close(void* hv) {
  Handle* h = (Handle*)hv;
  if (robust_lock(&h->hdr->mutex) == 0) {
    h->hdr->closed = 1;
    pthread_cond_broadcast(&h->hdr->not_empty);
    pthread_cond_broadcast(&h->hdr->not_full);
    pthread_mutex_unlock(&h->hdr->mutex);
  }
}

// Unmap; owner also unlinks the shm name.
void shmq_destroy(void* hv) {
  Handle* h = (Handle*)hv;
  int owner = h->owner;
  char name[256];
  std::memcpy(name, h->name, sizeof(name));
  munmap((void*)h->hdr, h->map_size);
  if (owner) shm_unlink(name);
  delete h;
}

}  // extern "C"
