"""ctypes binding for the native shared-memory message queue (shmqueue.cpp).

`ShmMessageQueue` moves byte messages between processes on one host through
a POSIX shm ring buffer — the native replacement for the reference's Redis
transport (reference rafiki/cache/cache.py). `available()` reports whether
the native library could be built; callers fall back to the in-process
Python broker otherwise.
"""

from __future__ import annotations

import ctypes
import logging
import os
import threading
import uuid
from typing import Optional

from rafiki_tpu.native.build import load_library

logger = logging.getLogger(__name__)

_DEFAULT_CAPACITY = 1 << 20  # 1 MiB ring


def default_capacity() -> int:
    """Ring capacity in bytes: RAFIKI_SHM_RING_BYTES, default 1 MiB.
    Read per call, not at import — batched binary frames (cache/wire.py)
    are bigger than per-query JSON, and an operator sizing the ring up
    for them must not need a process restart ordering dance."""
    try:
        return max(int(os.environ.get(
            "RAFIKI_SHM_RING_BYTES", _DEFAULT_CAPACITY)), 1 << 12)
    except ValueError:
        logger.error("ignoring unparseable RAFIKI_SHM_RING_BYTES=%r",
                     os.environ.get("RAFIKI_SHM_RING_BYTES"))
        return _DEFAULT_CAPACITY


def _lib():
    lib = load_library("shmqueue")
    if lib is None:
        return None
    if not getattr(lib, "_shmq_configured", False):
        lib.shmq_create.restype = ctypes.c_void_p
        lib.shmq_create.argtypes = [ctypes.c_char_p, ctypes.c_uint32]
        lib.shmq_open.restype = ctypes.c_void_p
        lib.shmq_open.argtypes = [ctypes.c_char_p]
        lib.shmq_push.restype = ctypes.c_int
        lib.shmq_push.argtypes = [ctypes.c_void_p, ctypes.c_char_p,
                                  ctypes.c_uint32, ctypes.c_long]
        lib.shmq_pop.restype = ctypes.c_int
        lib.shmq_pop.argtypes = [ctypes.c_void_p, ctypes.c_char_p,
                                 ctypes.c_uint32, ctypes.c_long,
                                 ctypes.POINTER(ctypes.c_uint32)]
        lib.shmq_used.restype = ctypes.c_uint64
        lib.shmq_used.argtypes = [ctypes.c_void_p]
        lib.shmq_close.argtypes = [ctypes.c_void_p]
        lib.shmq_destroy.argtypes = [ctypes.c_void_p]
        lib._shmq_configured = True
    return lib


def available() -> bool:
    return _lib() is not None


def make_queue_name(prefix: str = "rafiki") -> str:
    """A fresh shm object name (must start with '/', one component)."""
    return f"/{prefix}-{os.getpid()}-{uuid.uuid4().hex[:12]}"


class ShmQueueClosed(Exception):
    pass


class ShmMessageQueue:
    """One MPMC byte-message queue backed by POSIX shared memory."""

    def __init__(self, name: str, capacity: Optional[int] = None,
                 create: bool = True):
        lib = _lib()
        if lib is None:
            raise RuntimeError("native shmqueue unavailable (no toolchain)")
        self._lib = lib
        self.name = name
        self._create = create
        if capacity is None:
            capacity = default_capacity()
        #: ring size this handle was created with (0 when attached — the
        #: native header is not re-read on open)
        self.capacity = capacity if create else 0
        #: high-water mark of ring occupancy seen through THIS handle's
        #: pushes — the operator's early warning that batched frames are
        #: approaching the -3 oversized/ring-full regime
        self.used_bytes_hw = 0
        if create:
            self._h = lib.shmq_create(name.encode(), capacity)
        else:
            self._h = lib.shmq_open(name.encode())
        if not self._h:
            raise OSError(f"shmq_{'create' if create else 'open'}({name}) failed")
        # receive buffers are per-thread: concurrent pop() calls must not
        # share one buffer or a second pop overwrites it before .raw is read
        self._tls = threading.local()
        # in-flight native-call tracking: destroy() must not munmap the
        # segment while another thread is blocked inside shmq_push/pop —
        # that is a segfault, not an exception
        self._cv = threading.Condition()
        self._inflight = 0

    def _enter_native(self) -> None:
        with self._cv:
            if not self._h:
                raise ShmQueueClosed(self.name)
            self._inflight += 1

    def _exit_native(self) -> None:
        with self._cv:
            self._inflight -= 1
            if self._inflight == 0:
                self._cv.notify_all()

    def push(self, payload: bytes, timeout_s: float = 5.0) -> None:
        self._enter_native()
        try:
            rc = self._lib.shmq_push(self._h, payload, len(payload),
                                     int(timeout_s * 1000))
            if rc == 0:
                # a fast consumer may pop the message before shmq_used is
                # sampled; the ring still momentarily held it, so the
                # high-water is floored at this message's size
                used = max(int(self._lib.shmq_used(self._h)), len(payload))
                if used > self.used_bytes_hw:
                    self.used_bytes_hw = used
        finally:
            self._exit_native()
        if rc == -1:
            raise TimeoutError("shm queue full")
        if rc == -2:
            raise ShmQueueClosed(self.name)
        if rc == -3:
            raise ValueError(f"message of {len(payload)}B exceeds ring capacity")
        assert rc == 0, rc

    def pop(self, timeout_s: float = 0.5) -> Optional[bytes]:
        """One message, or None on timeout. Raises ShmQueueClosed when the
        queue is closed and drained."""
        buf = getattr(self._tls, "buf", None)
        if buf is None:
            buf = self._tls.buf = ctypes.create_string_buffer(64 * 1024)
        required = ctypes.c_uint32(0)
        self._enter_native()
        try:
            rc = self._lib.shmq_pop(self._h, buf, len(buf),
                                    int(timeout_s * 1000),
                                    ctypes.byref(required))
            while rc == -4:
                # grow receive buffer and retry: with concurrent consumers a
                # different (larger) message may be at head by the retry, so
                # loop, not a single retry
                buf = self._tls.buf = ctypes.create_string_buffer(
                    int(required.value))
                rc = self._lib.shmq_pop(self._h, buf, len(buf),
                                        int(timeout_s * 1000),
                                        ctypes.byref(required))
        finally:
            self._exit_native()
        if rc == -1:
            return None
        if rc == -2:
            raise ShmQueueClosed(self.name)
        assert rc >= 0, rc
        return buf.raw[:rc]

    def stats(self) -> dict:
        """Ring occupancy picture for ops surfaces (broker stats, doctor):
        capacity is 0 for attached (non-creator) handles."""
        return {
            "capacity": self.capacity,
            "used_bytes": self.used_bytes(),
            "used_bytes_hw": self.used_bytes_hw,
        }

    def used_bytes(self) -> int:
        try:
            self._enter_native()
        except ShmQueueClosed:
            return 0
        try:
            return int(self._lib.shmq_used(self._h))
        finally:
            self._exit_native()

    def close(self) -> None:
        if self._h:
            self._lib.shmq_close(self._h)

    def destroy(self) -> None:
        """Unmap (and unlink, if this handle created the segment). Waits for
        in-flight push/pop calls on this handle to return first — their
        blocking waits are bounded by their own timeouts; call close() before
        destroy() to wake them immediately."""
        with self._cv:
            if not self._h:
                return
            h, self._h = self._h, None  # new calls now raise ShmQueueClosed
            while self._inflight:
                if not self._cv.wait(timeout=10.0):
                    logger.warning(
                        "destroy(%s): %d native calls still in flight",
                        self.name, self._inflight)
                    break
        self._lib.shmq_destroy(h)

    def __del__(self):
        try:
            self.destroy()
        # lint: absorb(__del__ during interpreter teardown)
        except Exception:
            pass
