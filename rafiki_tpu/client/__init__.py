"""User-facing Python client SDK (L7, reference rafiki/client/)."""

from rafiki_tpu.client.client import Client  # noqa: F401
