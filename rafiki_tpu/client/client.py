"""Python client SDK — full REST wrapper over the admin API
(reference rafiki/client/client.py:29-737).

Capability parity: login/JWT, user CRUD, model CRUD (file upload/download),
train job CRUD + trials + best trials + logs + raw params download,
`load_trial_model` (reconstruct a trained model locally, reference
client.py:487-506), inference job CRUD, predict, advisor endpoints,
`stop_all_jobs`.
"""

from __future__ import annotations

import base64
import json
import threading
import time
from typing import Any, Dict, List, Optional, Tuple

import requests

from rafiki_tpu.sdk.model import load_model_class
from rafiki_tpu.sdk.params import load_params


class RafikiError(Exception):
    """Admin API error. ``status`` carries the HTTP status code when the
    admin answered at all (None for transport/parse failures), so callers
    can tell a missing route (404 — an old admin without the endpoint)
    from a transient refusal (e.g. a 503 overload shed)."""

    def __init__(self, message: str, status: Optional[int] = None):
        super().__init__(message)
        self.status = status


class AdminRecoveringError(RafikiError):
    """The admin answered 503 because its boot reconciliation (control-
    plane crash recovery) is still running. Retryable: poll
    :meth:`Client.wait_until_admin_ready` or just retry after the
    ``Retry-After`` interval."""


class AdminUnavailableError(RafikiError):
    """No configured admin address answered: every one refused the
    connection or shed as a hot standby within the failover window
    (``RAFIKI_ADMIN_FAILOVER_TIMEOUT_S``). Typed and retryable — a
    failover is usually in flight; :meth:`Client.wait_until_admin_ready`
    absorbs it while walking the address list."""


class GenerationStreamError(RafikiError):
    """A generation stream ended with a typed terminal error frame
    (mid-stream worker fault, stalled decode past the door's inter-token
    timeout). Tokens yielded before the fault are valid — the stream
    failed, not the transport."""


class RolloutInFlightError(RafikiError):
    """The admin answered 409: a rollout is already in flight for this
    inference job (exactly one at a time). Wait it out with
    :meth:`Client.wait_until_rollout_done` or abort it with
    :meth:`Client.abort_rollout`, then retry."""


class RolloutRolledBackError(RafikiError):
    """The rollout ended without reaching DONE: ``phase`` is
    ``ROLLED_BACK`` (the SLO judge fired — ``reason`` carries its
    verdict and the rollout's event log holds the signal snapshot) or
    ``ABORTED`` (job stopped / admin restarted mid-flight). The job
    keeps serving the incumbent version."""

    def __init__(self, message: str, phase: str, reason: Optional[str]):
        super().__init__(message)
        self.phase = phase
        self.reason = reason


class Client:
    def __init__(self, admin_host: str = "127.0.0.1", admin_port: int = 3000,
                 admin_addrs: Optional[List[str]] = None):
        """``admin_addrs`` (or the ``RAFIKI_ADMIN_ADDRS`` env, a comma
        list of ``host:port``) enables control-plane HA failover: calls
        walk the list in order on connection-refused and standby-503
        answers, following the leader hint those 503s carry. Explicit
        ``admin_host``/``admin_port`` arguments mean the caller picked
        one admin on purpose, so the env list only applies to a
        default-constructed client."""
        from rafiki_tpu import config as _config

        explicit = (admin_host != "127.0.0.1" or admin_port != 3000)
        if admin_addrs:
            addrs = list(admin_addrs)
        elif not explicit and _config.ADMIN_ADDRS:
            addrs = [a.strip() for a in _config.ADMIN_ADDRS.split(",")
                     if a.strip()]
        else:
            addrs = []
        if not addrs:
            addrs = [f"{admin_host}:{admin_port}"]
        self._addrs: List[str] = addrs
        self._active = 0  # index of the last address that answered
        self._base = f"http://{addrs[0]}"
        self._token: Optional[str] = None
        self.user: Optional[Dict[str, Any]] = None
        # pooled keep-alive connections: a fresh TCP connect per call would
        # cost setup latency AND a new server-side handler thread each time
        # (the admin server speaks HTTP/1.1 — admin/http.py). One Session
        # PER THREAD: requests.Session is not documented thread-safe, and a
        # Client is shared across threads (e.g. the placement agent's
        # status forwarder reports from per-service threads).
        self._tls = threading.local()
        # predict_direct's resolved (app, version) -> (host, port); see
        # that method for the invalidation rule
        self._predictor_ports: Dict[Any, Any] = {}

    @property
    def _http(self) -> requests.Session:
        s = getattr(self._tls, "session", None)
        if s is None:
            s = self._tls.session = requests.Session()
        return s

    # -- plumbing ----------------------------------------------------------

    def _call(
        self,
        method: str,
        path: str,
        body: Optional[Dict[str, Any]] = None,
        params: Optional[Dict[str, Any]] = None,
    ) -> Any:
        """One admin API call, with multi-address failover.

        The walk is safe for NON-idempotent calls too, because it only
        moves on in two cases where the request provably did not execute:
        connection refused (no server accepted it) and a standby/fenced
        503 (the door shed before dispatch). A request the leader started
        processing never retries. Standby 503s carry the leader's address
        — that hint is tried first, so failover is one extra hop."""
        from rafiki_tpu import config as _config

        headers = {}
        if self._token:
            headers["Authorization"] = f"Bearer {self._token}"
        multi = len(self._addrs) > 1
        deadline = (time.monotonic()
                    + float(_config.ADMIN_FAILOVER_TIMEOUT_S))
        # the walk order: last-known-good first, then the rest in config
        # order; a leader hint from a standby 503 jumps the queue
        last_refusal: Optional[str] = None
        while True:
            order = [self._addrs[(self._active + i) % len(self._addrs)]
                     for i in range(len(self._addrs))]
            hint_first: List[str] = []
            for addr in order:
                if addr in hint_first:
                    continue
                hint_first.append(addr)
            resp = None
            for addr in hint_first:
                try:
                    resp = self._http.request(
                        method, f"http://{addr}" + path, json=body,
                        params=params, headers=headers)
                except requests.ConnectionError as e:
                    # the connection was refused/reset before the request
                    # went out — it never executed, walking on is safe
                    last_refusal = f"{addr}: {e}"
                    continue
                try:
                    payload = resp.json()
                except ValueError:
                    raise RafikiError(
                        f"Bad response ({resp.status_code}): {resp.text}")
                if (resp.status_code == 503 and isinstance(payload, dict)
                        and payload.get("standby")):
                    # a hot standby (or a just-fenced ex-leader) shed the
                    # call before dispatch; follow its leader hint
                    last_refusal = f"{addr}: {payload.get('error')}"
                    hint = payload.get("leader")
                    if hint and hint not in self._addrs:
                        self._addrs.append(hint)
                    if hint and hint in self._addrs:
                        self._active = self._addrs.index(hint)
                    continue
                if (multi and resp.status_code == 503
                        and isinstance(payload, dict)
                        and "recovery" in payload):
                    # a just-promoted leader still reconciling its store:
                    # the recovery gate shed the call BEFORE dispatch, so
                    # retrying within the failover window is safe. Only in
                    # multi-address mode — single-admin clients keep the
                    # typed AdminRecoveringError contract.
                    last_refusal = f"{addr}: {payload.get('error')}"
                    continue
                self._active = self._addrs.index(addr)
                self._base = f"http://{addr}"
                return self._finish_call(resp, payload)
            if not multi and len(self._addrs) == 1:
                # single-admin client: no list to walk — surface the
                # refusal immediately, but TYPED (satellite of the HA
                # work: wait_until_admin_ready retries it like any other
                # RafikiError instead of leaking a transport exception)
                raise AdminUnavailableError(
                    f"admin unreachable: {last_refusal}")
            if time.monotonic() >= deadline:
                raise AdminUnavailableError(
                    "no admin address answered within "
                    f"{_config.ADMIN_FAILOVER_TIMEOUT_S:.0f}s failover "
                    f"window (last: {last_refusal}); tried {self._addrs}")
            time.sleep(0.1)

    def _finish_call(self, resp, payload) -> Any:
        if resp.status_code != 200:
            if resp.status_code == 503 and isinstance(payload, dict) \
                    and "recovery" in payload:
                # the admin restarted and is still reconciling its store
                # (admin/recovery.py): typed, so callers can wait it out
                raise AdminRecoveringError(
                    payload.get("error", "admin is recovering"))
            if resp.status_code == 409:
                # one live rollout per job (admin/rollout.py): typed so
                # callers can wait the current one out or abort it
                raise RolloutInFlightError(
                    payload.get("error", "rollout already in flight"),
                    status=409)
            raise RafikiError(payload.get("error", f"HTTP {resp.status_code}"),
                              status=resp.status_code)
        return payload.get("data")

    # -- auth --------------------------------------------------------------

    def login(self, email: str, password: str) -> Dict[str, Any]:
        data = self._call("POST", "/tokens", {"email": email, "password": password})
        self._token = data["token"]
        self.user = {"user_id": data["user_id"], "user_type": data["user_type"]}
        return self.user

    def logout(self) -> None:
        self._token = None
        self.user = None

    # -- users -------------------------------------------------------------

    def create_user(self, email: str, password: str, user_type: str) -> Dict:
        return self._call(
            "POST",
            "/users",
            {"email": email, "password": password, "user_type": user_type},
        )

    def get_users(self) -> List[Dict]:
        return self._call("GET", "/users")

    def ban_user(self, email: str) -> Dict:
        return self._call("DELETE", "/users", {"email": email})

    # -- models ------------------------------------------------------------

    def create_model(
        self,
        name: str,
        task: str,
        model_file_path: str,
        model_class: str,
        dependencies: Optional[Dict[str, Optional[str]]] = None,
        access_right: str = "PRIVATE",
    ) -> Dict:
        with open(model_file_path, "rb") as f:
            file_b64 = base64.b64encode(f.read()).decode()
        return self._call(
            "POST",
            "/models",
            {
                "name": name,
                "task": task,
                "model_file_base64": file_b64,
                "model_class": model_class,
                "dependencies": dependencies,
                "access_right": access_right,
            },
        )

    def verify_model(
        self,
        model_file_path: str,
        model_class: str,
        dependencies: Optional[Dict[str, Optional[str]]] = None,
    ) -> Dict:
        """Dry-run the admin's template verifier (static analysis, no
        code execution server-side): returns {"mode", "ok", "findings",
        "capabilities", ...} and never creates a model row — iterate
        locally until ``ok`` before spending an upload (or run
        ``python -m rafiki_tpu.analysis file.py`` offline)."""
        with open(model_file_path, "rb") as f:
            file_b64 = base64.b64encode(f.read()).decode()
        return self._call(
            "POST",
            "/models/verify",
            {
                "model_file_base64": file_b64,
                "model_class": model_class,
                "dependencies": dependencies,
            },
        )

    def get_models(self, task: Optional[str] = None) -> List[Dict]:
        return self._call("GET", "/models", params={"task": task} if task else None)

    def get_model(self, name: str) -> Dict:
        return self._call("GET", f"/models/{name}")

    def download_model_file(self, name: str) -> bytes:
        data = self._call("GET", f"/models/{name}/file")
        return base64.b64decode(data["model_file_base64"])

    def delete_model(self, name: str) -> None:
        self._call("DELETE", f"/models/{name}")

    # -- train jobs ----------------------------------------------------------

    def create_train_job(
        self,
        app: str,
        task: str,
        train_dataset_uri: str,
        test_dataset_uri: str,
        budget: Optional[Dict[str, Any]] = None,
        models: Optional[List[str]] = None,
    ) -> Dict:
        return self._call(
            "POST",
            "/train_jobs",
            {
                "app": app,
                "task": task,
                "train_dataset_uri": train_dataset_uri,
                "test_dataset_uri": test_dataset_uri,
                "budget": budget,
                "models": models,
            },
        )

    def get_train_jobs(self) -> List[Dict]:
        """All of this user's train jobs, newest first (the dashboard's
        landing view)."""
        return self._call("GET", "/train_jobs")

    def get_train_jobs_of_app(self, app: str) -> List[Dict]:
        return self._call("GET", f"/train_jobs/{app}")

    def get_train_job(self, app: str, app_version: int = -1) -> Dict:
        return self._call("GET", f"/train_jobs/{app}/{app_version}")

    def stop_train_job(self, app: str, app_version: int = -1) -> Dict:
        return self._call("POST", f"/train_jobs/{app}/{app_version}/stop")

    def get_trials_of_train_job(self, app: str, app_version: int = -1) -> List[Dict]:
        return self._call("GET", f"/train_jobs/{app}/{app_version}/trials")

    def get_best_trials_of_train_job(
        self, app: str, app_version: int = -1, max_count: int = 2
    ) -> List[Dict]:
        return self._call(
            "GET",
            f"/train_jobs/{app}/{app_version}/best_trials",
            params={"max_count": max_count},
        )

    # -- trials ----------------------------------------------------------------

    def get_trial(self, trial_id: str) -> Dict:
        return self._call("GET", f"/trials/{trial_id}")

    def get_trial_logs(self, trial_id: str) -> Dict:
        return self._call("GET", f"/trials/{trial_id}/logs")

    def get_trial_trace(self, trial_id: str) -> List[Dict]:
        """Per-phase span breakdown of a trial (propose/train/evaluate/
        persist wall-clock) — no reference analogue (SURVEY.md §5.1)."""
        return self._call("GET", f"/trials/{trial_id}/trace")

    def download_trial_params(self, trial_id: str) -> bytes:
        data = self._call("GET", f"/trials/{trial_id}/parameters")
        return base64.b64decode(data["params_base64"])

    def load_trial_model(self, trial_id: str, model_name: str):
        """Reconstruct a trained model locally (reference client.py:487-506):
        download the template file + the trial's params, instantiate with the
        trial's knobs, restore parameters."""
        trial = self.get_trial(trial_id)
        model_bytes = self.download_model_file(model_name)
        model_info = self.get_model(model_name)
        clazz = load_model_class(model_bytes, model_info["model_class"])
        model = clazz(**trial["knobs"])
        model.load_parameters(load_params(self.download_trial_params(trial_id)))
        return model

    # -- inference jobs ----------------------------------------------------------

    def create_inference_job(self, app: str, app_version: int = -1,
                             budget: Optional[Dict] = None) -> Dict:
        """``budget={"CHIPS_PER_WORKER": n}`` serves each worker on an
        n-chip mesh (sharded predict) — see Admin.create_inference_job."""
        body = {"app": app, "app_version": app_version}
        if budget is not None:
            body["budget"] = budget
        return self._call("POST", "/inference_jobs", body)

    def get_inference_job(self, app: str, app_version: int = -1) -> Dict:
        return self._call("GET", f"/inference_jobs/{app}/{app_version}")

    def get_inference_job_stats(self, app: str, app_version: int = -1) -> Dict:
        """Serving counters: per-worker batches/queries and batch occupancy."""
        return self._call("GET", f"/inference_jobs/{app}/{app_version}/stats")

    def stop_inference_job(self, app: str, app_version: int = -1) -> Dict:
        return self._call("POST", f"/inference_jobs/{app}/{app_version}/stop")

    def scale_inference_job(self, app: str, delta: int,
                            app_version: int = -1) -> Dict:
        """Elastically add (``delta`` > 0) or gracefully drain
        (``delta`` < 0) serving replicas of the app's running inference
        job — no redeploy, in-flight requests complete or re-route. The
        answer carries the replicas added/removed, chips borrowed from /
        returned to the training plane, and the new live replica count.
        (The RAFIKI_AUTOSCALE control loop drives this same primitive
        automatically; see GET /fleet/health's "autoscaler" section.)"""
        return self._call(
            "POST", f"/inference_jobs/{app}/{app_version}/scale",
            {"delta": int(delta)})

    # -- safe live rollouts (docs/failure-model.md "Rollout faults") ---------

    def update_inference_job(
        self, app: str, trial_id: str, app_version: int = -1,
        canary_fraction: Optional[float] = None,
        batch: Optional[int] = None,
    ) -> Dict:
        """Update the app's RUNNING inference job to serve ``trial_id``
        in place: one canary replica takes ``canary_fraction`` of the
        traffic while an SLO judge compares it to the incumbents, then a
        rolling replace in ``batch``-sized steps — zero dropped requests,
        automatic rollback on a breach. Returns the rollout row (phase
        ``CANARY``) immediately; follow with
        :meth:`wait_until_rollout_done`. Raises the typed
        :class:`RolloutInFlightError` (HTTP 409) while another rollout
        of the same job is live."""
        body: Dict[str, Any] = {"trial_id": trial_id}
        if canary_fraction is not None:
            body["canary_fraction"] = float(canary_fraction)
        if batch is not None:
            body["batch"] = int(batch)
        return self._call(
            "POST", f"/inference_jobs/{app}/{app_version}/update", body)

    def get_rollout(self, app: str, app_version: int = -1) -> Dict:
        """The app's newest rollout (live phases carry the judge's
        per-lane signal snapshot under ``signals``)."""
        return self._call(
            "GET", f"/inference_jobs/{app}/{app_version}/rollout")

    def abort_rollout(self, app: str, app_version: int = -1) -> Dict:
        """Abort the in-flight rollout: the new version is drained and
        the incumbents restored (phase ``ROLLED_BACK``, reason
        "operator abort")."""
        return self._call(
            "POST", f"/inference_jobs/{app}/{app_version}/rollout/abort")

    def ack_rollout(self, app: str, app_version: int = -1) -> Dict:
        """Acknowledge the newest rolled-back rollout (clears the
        ``python -m rafiki_tpu.doctor`` WARN)."""
        return self._call(
            "POST", f"/inference_jobs/{app}/{app_version}/rollout/ack")

    def get_drift_status(self, app: str, app_version: int = -1) -> Dict:
        """The app's drift closed-loop state (admin/drift.py): phase,
        frozen-baseline flag, live divergence signals, event tail."""
        return self._call(
            "GET", f"/inference_jobs/{app}/{app_version}/drift")

    def ack_drift(self, app: str, app_version: int = -1) -> Dict:
        """Acknowledge the app's drift loop: re-arms a ``PARKED`` loop
        or clears a rollback-flap streak (clears the doctor WARNs)."""
        return self._call(
            "POST", f"/inference_jobs/{app}/{app_version}/drift/ack")

    def wait_until_rollout_done(
        self, app: str, app_version: int = -1, timeout_s: float = 300.0,
    ) -> Dict:
        """Poll until the app's rollout reaches a terminal phase.
        Returns the rollout row on ``DONE``; raises the typed
        :class:`RolloutRolledBackError` — carrying the judge's reason —
        on ``ROLLED_BACK``/``ABORTED``, and TimeoutError if it is still
        live after ``timeout_s``."""
        import time as _time

        deadline = _time.monotonic() + timeout_s
        while True:
            rollout = self.get_rollout(app, app_version)
            phase = rollout.get("phase")
            if phase == "DONE":
                return rollout
            if phase in ("ROLLED_BACK", "ABORTED"):
                raise RolloutRolledBackError(
                    f"rollout {rollout.get('id', '?')[:8]} ended "
                    f"{phase}: {rollout.get('reason')}",
                    phase=phase, reason=rollout.get("reason"))
            if _time.monotonic() > deadline:
                raise TimeoutError(
                    f"rollout still {phase} after {timeout_s:.0f}s")
            _time.sleep(0.1)

    def predict(
        self, app: str, queries: List[Any], app_version: int = -1
    ) -> List[Any]:
        data = self._call(
            "POST",
            f"/predict/{app}",
            {"queries": queries, "app_version": app_version},
        )
        return data["predictions"]

    def _dedicated_door(self, app: str, app_version: int):
        """Resolve (and TTL-cache) the app's dedicated predictor door as
        ``(host, port, expiry)`` — shared by :meth:`predict_direct` and
        :meth:`generate`; entries drop on any request failure so a moved
        door re-resolves within seconds."""
        import time as _time

        from rafiki_tpu import config as _config

        key = (app, app_version)
        cached = self._predictor_ports.get(key)
        now = _time.monotonic()
        if cached is None or cached[2] < now:
            inf = self.get_inference_job(app, app_version)
            host, port = inf.get("predictor_host"), inf.get("predictor_port")
            if not host or not port:
                raise RafikiError(
                    f"inference job for {app} has no dedicated predictor "
                    f"port (deployment runs without RAFIKI_PREDICTOR_PORTS)")
            cached = (host, port, now + _config.PREDICT_ROUTE_TTL_S)
            self._predictor_ports[key] = cached
        return cached

    def predict_direct(
        self, app: str, queries: Any, app_version: int = -1
    ) -> List[Any]:
        """Predict through the job's DEDICATED predictor port, bypassing
        the admin control-plane server (available when the deployment set
        RAFIKI_PREDICTOR_PORTS=1; reference parity: per-job published
        predictor ports, reference admin/services_manager.py:379-384).
        ``queries`` is a JSON list — or a numpy array (leading batch
        axis), which ships as one binary ``.npy`` body and skips JSON
        float formatting entirely (the serving-door CPU cost for dense
        queries).
        The same login token authorizes both doors. The resolved
        host:port is cached per (app, version) with the same short TTL
        the admin door uses for its predict route
        (``PREDICT_ROUTE_TTL_S``) — one control-plane GET per TTL
        window, not per predict — and dropped on any failure, so a
        redeploy (or an app_version=-1 'latest' that moved) re-resolves
        within seconds rather than serving a stale port forever."""
        key = (app, app_version)
        cached = self._dedicated_door(app, app_version)
        headers = {}
        if self._token:
            headers["Authorization"] = f"Bearer {self._token}"
        import numpy as _np

        body_kwargs: Dict[str, Any]
        if isinstance(queries, _np.ndarray):
            # binary door: ship the batch as one .npy body — no JSON
            # float formatting/parsing on either side (the serving CPU
            # cost for dense queries like images) — and ask for the
            # predictions back the same way (Accept negotiation; the
            # door falls back to JSON for ragged predictions, so the
            # response Content-Type is sniffed below). Encode OUTSIDE
            # the request try: a local encode error (object dtype etc.)
            # is the caller's bug, not a route failure
            import io

            buf = io.BytesIO()
            try:
                _np.save(buf, queries, allow_pickle=False)
            except ValueError as e:
                raise RafikiError(f"queries array not npy-encodable: {e}")
            headers["Content-Type"] = "application/x-npy"
            headers["Accept"] = "application/x-npy, application/json"
            body_kwargs = {"data": buf.getvalue()}
        else:
            body_kwargs = {"json": {"queries": queries}}
        try:
            resp = self._http.request(
                "POST", f"http://{cached[0]}:{cached[1]}/predict",
                headers=headers, **body_kwargs)
            rtype = (resp.headers.get("Content-Type") or "").split(";")[0]
            if resp.status_code == 200 and rtype == "application/x-npy":
                import io

                arr = _np.load(io.BytesIO(resp.content), allow_pickle=False)
                return list(arr)
            payload = resp.json()
        except (requests.RequestException, ValueError) as e:
            # connect failure OR an undecodable body (port reclaimed by
            # some other server): drop the route and surface the door's
            # error type, same contract as every _call path
            self._predictor_ports.pop(key, None)
            raise RafikiError(f"dedicated predictor unreachable: {e}")
        if resp.status_code != 200:
            self._predictor_ports.pop(key, None)
            raise RafikiError(payload.get("error",
                                          f"HTTP {resp.status_code}"))
        return payload["data"]["predictions"]

    def generate(self, app: str, prompt_ids: List[int],
                 max_tokens: Optional[int] = None, app_version: int = -1,
                 timeout_s: Optional[float] = None, binary: bool = False,
                 temperature: Optional[float] = None,
                 top_k: Optional[int] = None,
                 top_p: Optional[float] = None,
                 seed: Optional[int] = None):
        """Stream a ``TEXT_GENERATION`` completion token-by-token through
        the app's dedicated predictor door (POST /generate, chunked
        transfer). Yields one delta dict per emitted increment —
        ``{"tokens": [...], "finished": bool, "reason": ...}`` — the
        moment it arrives, so the first token lands long before a long
        completion ends.

        ``binary=True`` opts into length-prefixed v3 wire token-delta
        frames instead of JSON lines (the zero-parse path; old doors that
        ignore the Accept header still answer JSON — the frame sniff
        handles either). A typed terminal error frame (mid-stream worker
        fault, stalled decode) raises :class:`GenerationStreamError`
        after yielding every token received before the fault.

        ``temperature`` / ``top_k`` / ``top_p`` turn on real sampling
        (temperature=0 or unset = greedy); a fixed ``seed`` makes the
        sampled stream reproducible — and the platform keeps it stable
        across mid-stream preemption/resume, so the sequence is exactly
        the uncontended one either way.

        Stream continuity (docs/failure-model.md "Stream continuity"):
        the door journals the stream and transparently resumes it
        token-identically on a sibling replica if its worker dies or is
        drained/retired mid-stream — the client just keeps receiving
        deltas. Only when the bounded resume is exhausted (or refused:
        the stream's model version has no replica left) does the typed
        terminal error frame arrive."""
        key = (app, app_version)
        host, port, _ = self._dedicated_door(app, app_version)
        headers = {}
        if self._token:
            headers["Authorization"] = f"Bearer {self._token}"
        body: Dict[str, Any] = {"prompt_ids": list(prompt_ids)}
        if max_tokens is not None:
            body["max_tokens"] = int(max_tokens)
        if timeout_s is not None:
            body["timeout_s"] = float(timeout_s)
        if temperature is not None:
            body["temperature"] = float(temperature)
        if top_k is not None:
            body["top_k"] = int(top_k)
        if top_p is not None:
            body["top_p"] = float(top_p)
        if seed is not None:
            body["seed"] = int(seed)
        if binary:
            from rafiki_tpu.cache import wire

            headers["Accept"] = wire.CONTENT_TYPE
        try:
            resp = self._http.request(
                "POST", f"http://{host}:{port}/generate",
                headers=headers, json=body, stream=True)
        except requests.RequestException as e:
            self._predictor_ports.pop(key, None)
            raise RafikiError(f"dedicated predictor unreachable: {e}")
        with resp:
            if resp.status_code != 200:
                self._predictor_ports.pop(key, None)
                try:
                    payload = resp.json()
                except ValueError:
                    payload = {}
                raise RafikiError(
                    payload.get("error", f"HTTP {resp.status_code}"),
                    status=resp.status_code)
            ctype = (resp.headers.get("Content-Type") or "").split(";")[0]
            deltas = (self._iter_wire_deltas(resp)
                      if ctype == "application/x-rafiki-wire"
                      else self._iter_json_deltas(resp))
            try:
                yield from deltas
            except requests.RequestException as e:
                # the stream was cut by the TRANSPORT (door/worker host
                # died mid-chunk — no terminal delta arrived): typed like
                # every other route failure, and the cached door is
                # suspect, so drop it for the next call
                self._predictor_ports.pop(key, None)
                raise RafikiError(
                    f"generation stream cut mid-transfer: {e}")

    @staticmethod
    def _iter_json_deltas(resp):
        buf = b""
        for data in resp.iter_content(chunk_size=None):
            buf += data
            while b"\n" in buf:
                line, buf = buf.split(b"\n", 1)
                if not line.strip():
                    continue
                try:
                    delta = json.loads(line)
                except ValueError as e:
                    raise RafikiError(f"garbled stream delta: {e}")
                if delta.get("error"):
                    raise GenerationStreamError(delta["error"])
                yield delta
                if delta.get("finished"):
                    return

    @staticmethod
    def _iter_wire_deltas(resp):
        from rafiki_tpu.cache import wire

        buf = b""
        for data in resp.iter_content(chunk_size=None):
            buf += data
            while len(buf) >= 4:
                n = int.from_bytes(buf[:4], "little")
                if len(buf) < 4 + n:
                    break
                frame, buf = buf[4:4 + n], buf[4 + n:]
                try:
                    _, delta = wire.decode_token_delta(frame)
                except wire.WireFormatError as e:
                    raise RafikiError(f"garbled token-delta frame: {e}")
                if delta.error is not None:
                    raise GenerationStreamError(delta.error)
                yield delta.to_json()
                if delta.finished:
                    return

    # -- advisors (reference client.py:586-644) ----------------------------------

    def create_advisor(
        self, knob_config_json: Dict[str, Any], advisor_id: Optional[str] = None
    ) -> str:
        data = self._call(
            "POST",
            "/advisors",
            {"knob_config": knob_config_json, "advisor_id": advisor_id},
        )
        return data["advisor_id"]

    def propose_knobs(self, advisor_id: str) -> Dict[str, Any]:
        return self._call("POST", f"/advisors/{advisor_id}/propose")["knobs"]

    def propose_knobs_batch(self, advisor_id: str,
                            k: int) -> List[Dict[str, Any]]:
        """K concurrent knob proposals in one call (vectorized trial
        execution: the worker trains the batch as one vmapped program).
        Admins predating the batch route answer 404 — callers fall back
        to K :meth:`propose_knobs` calls (RemoteAdvisorStore does this
        automatically)."""
        return self._call(
            "POST", f"/advisors/{advisor_id}/propose_batch",
            {"k": int(k)})["knobs_list"]

    def feedback_knobs_batch(
        self, advisor_id: str,
        items: List[Tuple[Dict[str, Any], float]],
    ) -> int:
        """Record a batch of (knobs, score) observations; returns how
        many were applied."""
        return int(self._call(
            "POST", f"/advisors/{advisor_id}/feedback_batch",
            {"items": [{"knobs": kn, "score": float(s)}
                       for kn, s in items]})["count"])

    def replay_advisor_feedback(self, advisor_id: str, items,
                                infeasible=None) -> bool:
        """Seed a fresh advisor session with already-scored (knobs, score)
        pairs; no-op (False) if the session already has observations.
        ``infeasible`` — (knobs, fault_kind) pairs of scoreless failures
        — rides the same empty-only guard."""
        out = self._call(
            "POST",
            f"/advisors/{advisor_id}/replay",
            {"items": [{"knobs": k, "score": s} for k, s in items],
             "infeasible": [{"knobs": k, "kind": kind}
                            for k, kind in infeasible or []]},
        )
        return bool(out["replayed"])

    def feedback_infeasible_knobs(
        self, advisor_id: str, knobs: Dict[str, Any], kind: str = "USER",
        trial_id: Optional[str] = None,
    ) -> int:
        """Tell the advisor the trial at ``knobs`` failed without a
        usable score (fault taxonomy kind USER/TIMEOUT/INVALID_SCORE);
        proposals steer away. Returns the session's infeasible count."""
        return int(self._call(
            "POST",
            f"/advisors/{advisor_id}/infeasible",
            {"knobs": knobs, "kind": kind, "trial_id": trial_id},
        )["infeasible"])

    def feedback_knobs(
        self, advisor_id: str, knobs: Dict[str, Any], score: float
    ) -> Dict[str, Any]:
        return self._call(
            "POST",
            f"/advisors/{advisor_id}/feedback",
            {"knobs": knobs, "score": score},
        )["knobs"]

    def report_rung(self, advisor_id: str, trial_id: str, resource: int,
                    value: float, min_resource: int = 1, eta: int = 3,
                    mode: str = "min") -> bool:
        """ASHA early-stop rung report; returns whether the trial should
        continue training."""
        return bool(self._call(
            "POST",
            f"/advisors/{advisor_id}/report_rung",
            {"trial_id": trial_id, "resource": int(resource),
             "value": float(value), "min_resource": int(min_resource),
             "eta": int(eta), "mode": mode},
        )["keep"])

    def delete_advisor(self, advisor_id: str) -> None:
        self._call("DELETE", f"/advisors/{advisor_id}")

    # -- misc --------------------------------------------------------------------

    def get_fleet_health(self) -> Dict[str, Any]:
        """Operator view: per-agent heartbeat/breaker state, the serving
        overload picture, and the boot-reconciliation report (admin-rights
        token required; GET /fleet/health)."""
        return self._call("GET", "/fleet/health")

    def wait_until_admin_ready(self, timeout_s: float = 60.0) -> Dict[str, Any]:
        """Block until a (re)starting admin finishes its boot
        reconciliation (recovery state `ready` on the public root) —
        no credentials needed, so deploy scripts can gate on it before
        logging in. Returns the public recovery state ({"state": ...});
        the full report lives behind :meth:`get_fleet_health`.

        With control-plane HA the underlying call walks the whole
        ``RAFIKI_ADMIN_ADDRS`` list (typed ``AdminUnavailableError``
        refusals are absorbed like any other transient), so this also
        waits out a leader failover, not just a restart."""
        import time as _time

        deadline = _time.monotonic() + timeout_s
        while True:
            try:
                data = self._call("GET", "/")
                rec = (data or {}).get("recovery") or {"state": "ready"}
                if rec.get("state") != "recovering":
                    return rec
            except (RafikiError, requests.RequestException):
                # not up yet (connection refused while the socket rebinds)
                # or transient — keep polling
                pass
            if _time.monotonic() > deadline:
                raise TimeoutError(
                    f"admin still recovering after {timeout_s:.0f}s")
            _time.sleep(0.1)

    def send_event(self, name: str, **payload: Any) -> None:
        self._call("POST", f"/event/{name}", payload)

    def stop_all_jobs(self) -> None:
        """Stop all running train and inference jobs (admin-only; reference
        client.py:647 / scripts/stop_all_jobs.py)."""
        self._call("POST", "/actions/stop_all_jobs")
