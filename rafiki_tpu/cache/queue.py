"""Continuous-batching query transport.

The reference's serving data plane was Redis lists polled on 0.25 s sleeps on
*both* sides (reference rafiki/cache/cache.py:36-78, predictor/predictor.py:46-59,
worker/inference.py:43-65), giving every request a ~0.25-0.5 s latency floor
before any model time. Here the transport is a condition-variable handoff:

- the predictor submits each request's queries atomically (submit_many) and
  gets futures back;
- each inference worker blocks on its queue, waking the moment work arrives,
  and drains whatever has queued (continuous batching self-paces: queries
  accumulate during the previous dispatch, so batches fill under load while
  single queries never wait — the optional deadline adds a coalescing wait
  only if an operator asks for one);
- workers resolve futures directly — no scan-and-remove.

``Broker`` is the seam (the reference's Cache class shape, reference
cache/cache.py:10-79): `InProcessBroker` serves the single-host stack; a
remote broker implementing the same interface can back multi-host serving.
"""

from __future__ import annotations

import abc
import threading
import time
from typing import Any, Dict, List, Optional, Tuple


class QueryFuture:
    """A pending prediction for one query."""

    __slots__ = ("_event", "_value", "_error")

    def __init__(self) -> None:
        self._event = threading.Event()
        self._value: Any = None
        self._error: Optional[BaseException] = None

    def set_result(self, value: Any) -> None:
        self._value = value
        self._event.set()

    def set_error(self, error: BaseException) -> None:
        self._error = error
        self._event.set()

    def result(self, timeout: Optional[float] = None) -> Any:
        if not self._event.wait(timeout):
            raise TimeoutError("prediction timed out")
        if self._error is not None:
            raise self._error
        return self._value


class WorkerQueue:
    """A single inference worker's inbox of (future, query) pairs."""

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._cond = threading.Condition(self._lock)
        self._items: List[Tuple[QueryFuture, Any]] = []
        self._closed = False

    def submit(self, query: Any) -> QueryFuture:
        return self.submit_many([query])[0]

    def submit_many(self, queries: List[Any]) -> List[QueryFuture]:
        """Enqueue a whole request's queries atomically (one lock, one
        wake-up). A per-query submit loop can lose a race with the worker:
        it wakes after the first item, serves a singleton batch, and the
        rest of the request waits a full dispatch behind it — with the
        batch deadline at 0 (serve immediately), atomic enqueue is what
        keeps one request one batch."""
        futs = [QueryFuture() for _ in queries]
        with self._cond:
            if self._closed:
                for fut in futs:
                    fut.set_error(RuntimeError("worker queue closed"))
                return futs
            self._items.extend(zip(futs, queries))
            self._cond.notify()
        return futs

    def take_batch(
        self,
        max_size: int,
        deadline_s: float,
        wait_timeout_s: float = 0.5,
    ) -> Optional[List[Tuple[QueryFuture, Any]]]:
        """Block until work arrives (or `wait_timeout_s` elapses), then keep
        draining until the batch fills or `deadline_s` passes since the first
        item. Returns [] on timeout so callers can check stop flags, and
        None once the queue is CLOSED and drained — a closed queue answers
        instantly, so treating it like a timeout would turn the caller's
        poll loop into a busy spin."""
        with self._cond:
            if not self._items and not self._closed:
                self._cond.wait(wait_timeout_s)
            if not self._items:
                return None if self._closed else []
            first_t = time.monotonic()
            batch = self._items[:max_size]
            del self._items[: len(batch)]
            while len(batch) < max_size and not self._closed:
                remaining = deadline_s - (time.monotonic() - first_t)
                if remaining <= 0:
                    break
                if not self._items:
                    self._cond.wait(remaining)
                take = min(max_size - len(batch), len(self._items))
                if take:
                    batch.extend(self._items[:take])
                    del self._items[:take]
            return batch

    def close(self) -> None:
        with self._cond:
            self._closed = True
            for fut, _ in self._items:
                fut.set_error(RuntimeError("worker queue closed"))
            self._items.clear()
            self._cond.notify_all()


class Broker(abc.ABC):
    """Transport seam between predictors and inference workers."""

    @abc.abstractmethod
    def register_worker(self, inference_job_id: str, worker_id: str) -> WorkerQueue:
        ...

    @abc.abstractmethod
    def unregister_worker(self, inference_job_id: str, worker_id: str) -> None:
        ...

    @abc.abstractmethod
    def get_worker_queues(self, inference_job_id: str) -> Dict[str, WorkerQueue]:
        ...


class InProcessBroker(Broker):
    """Single-host broker: queues live in process memory."""

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._queues: Dict[str, Dict[str, WorkerQueue]] = {}

    def register_worker(self, inference_job_id: str, worker_id: str) -> WorkerQueue:
        with self._lock:
            q = WorkerQueue()
            self._queues.setdefault(inference_job_id, {})[worker_id] = q
            return q

    def unregister_worker(self, inference_job_id: str, worker_id: str) -> None:
        with self._lock:
            q = self._queues.get(inference_job_id, {}).pop(worker_id, None)
        if q is not None:
            q.close()

    def get_worker_queues(self, inference_job_id: str) -> Dict[str, WorkerQueue]:
        with self._lock:
            return dict(self._queues.get(inference_job_id, {}))
