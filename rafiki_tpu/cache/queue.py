"""Continuous-batching query transport.

The reference's serving data plane was Redis lists polled on 0.25 s sleeps on
*both* sides (reference rafiki/cache/cache.py:36-78, predictor/predictor.py:46-59,
worker/inference.py:43-65), giving every request a ~0.25-0.5 s latency floor
before any model time. Here the transport is a condition-variable handoff:

- the predictor submits each request's queries atomically (submit_many) and
  gets futures back;
- each inference worker blocks on its queue, waking the moment work arrives,
  and drains whatever has queued (continuous batching self-paces: queries
  accumulate during the previous dispatch, so batches fill under load while
  single queries never wait — the optional deadline adds a coalescing wait
  only if an operator asks for one);
- workers resolve futures directly — no scan-and-remove.

``Broker`` is the seam (the reference's Cache class shape, reference
cache/cache.py:10-79): `InProcessBroker` serves the single-host stack; a
remote broker implementing the same interface can back multi-host serving.
"""

from __future__ import annotations

import abc
import copy
import threading
import time
from typing import Any, Dict, List, Optional, Tuple


class QueueFullError(RuntimeError):
    """A bounded worker queue refused a submit (overload shed signal).

    Carries ``retry_after_s`` so the HTTP doors can answer the shed with
    ``429`` + a concrete ``Retry-After`` instead of a bare refusal."""

    def __init__(self, message: str, retry_after_s: float = 1.0) -> None:
        super().__init__(message)
        self.retry_after_s = max(float(retry_after_s), 0.0)


class FrameTooLargeError(ValueError):
    """A single wire frame exceeds the shm ring capacity — a PERMANENT
    condition for this request (retrying the same payload can never
    succeed), unlike the transient, retryable :class:`QueueFullError`.
    The doors answer it with 413: split the request or raise
    ``RAFIKI_SHM_RING_BYTES``."""


class QueryFuture:
    """A pending prediction for one query.

    ``trace`` carries the request's RequestTrace (utils/trace.py) when
    the request is sampled — in-process workers read it off the future to
    record batch-assembly/forward spans straight into the door's span
    tree; it is None for unsampled traffic."""

    __slots__ = ("_event", "_value", "_error", "trace")

    def __init__(self) -> None:
        self._event = threading.Event()
        self._value: Any = None
        self._error: Optional[BaseException] = None
        self.trace = None

    def set_result(self, value: Any) -> None:
        self._value = value
        self._event.set()

    def set_error(self, error: BaseException) -> None:
        self._error = error
        self._event.set()

    def result(self, timeout: Optional[float] = None) -> Any:
        if not self._event.wait(timeout):
            raise TimeoutError("prediction timed out")
        if self._error is not None:
            # A failed batch shares ONE exception instance across all of
            # its futures, and hedged gathers re-raise from several waiter
            # threads at once — raising the shared instance would have
            # every raise splice a different waiter's frames into the same
            # __traceback__. Raise a per-waiter copy chained to the
            # original, so each waiter owns its traceback and the causal
            # (worker-side) one stays pristine on __cause__.
            try:
                mine = copy.copy(self._error)
            except Exception:
                raise self._error  # uncopyable exotic exception
            if type(mine) is not type(self._error):
                raise self._error
            raise mine from self._error
        return self._value


class GenerationError(RuntimeError):
    """A generation stream ended with a typed terminal fault (mid-stream
    worker error, stalled decode, malformed request). The streaming door
    maps it to a terminal error frame on the open response — never a
    silent hang — and :meth:`TokenStream.next_delta` re-raises it."""


#: Typed eviction/terminal reason for a stream handed BACK to the door by
#: a retiring replica (drain handoff, docs/failure-model.md "Stream
#: continuity"): the stream is not finished and not failed — it wants to
#: continue on a sibling replica via the door's resume journal.
REASON_MIGRATING = "migrating"


class StreamMigratingError(GenerationError):
    """INFRA-class terminal: the serving replica handed the stream back
    (``reason="migrating"``) instead of finishing it — drain, scale-down,
    or rollout retirement. The door's resume journal catches this
    *before* any client-visible frame and re-routes the stream to a
    sibling replica; it only surfaces to the client (as a plain
    :class:`GenerationError`) when every resume attempt is exhausted."""


class TokenDelta:
    """One increment of a generation stream: the token ids emitted since
    the previous delta, plus the terminal flags. ``finished`` is True on
    the stream's LAST delta; ``reason`` then says why (``eos`` |
    ``max_tokens`` | ``context`` | ``deadline`` | ``error`` |
    ``cancelled`` | ``migrating``) and ``error`` carries the fault text
    when reason is ``error`` or ``migrating``."""

    __slots__ = ("tokens", "finished", "reason", "error")

    def __init__(self, tokens: List[int], finished: bool = False,
                 reason: Optional[str] = None,
                 error: Optional[str] = None) -> None:
        self.tokens = list(tokens)
        self.finished = bool(finished)
        self.reason = reason
        self.error = error

    def to_json(self) -> Dict[str, Any]:
        out: Dict[str, Any] = {"tokens": self.tokens,
                               "finished": self.finished}
        if self.reason is not None:
            out["reason"] = self.reason
        if self.error is not None:
            out["error"] = self.error
        return out


class TokenStream:
    """The per-sequence channel between the generation worker's slot
    scheduler and the streaming door.

    The worker PUSHES :class:`TokenDelta` increments (and exactly one
    terminal delta: ``finished=True`` or a typed failure); the door PULLS
    with :meth:`next_delta` and writes each increment to the chunked HTTP
    response as it lands. ``cancel()`` is the consumer's back-signal — a
    client that disconnected mid-stream — which the scheduler reads to
    evict the slot instead of decoding for nobody."""

    __slots__ = ("seq_id", "_cond", "_deltas", "_finished", "_cancelled")

    def __init__(self, seq_id: str) -> None:
        self.seq_id = seq_id
        self._cond = threading.Condition()
        self._deltas: List[TokenDelta] = []
        self._finished = False
        self._cancelled = False

    def push(self, tokens: List[int], finished: bool = False,
             reason: Optional[str] = None) -> None:
        """Worker side: append one increment (terminal when ``finished``).
        Pushes after the terminal delta are dropped — a scheduler racing a
        door-side cancel must not resurrect a closed stream."""
        with self._cond:
            if self._finished:
                return
            self._deltas.append(TokenDelta(tokens, finished, reason))
            self._finished = self._finished or finished
            self._cond.notify_all()

    def fail(self, message: str) -> None:
        """Worker side: terminal typed fault — the stream ends with an
        error delta (reason ``error``), never a silent stop."""
        with self._cond:
            if self._finished:
                return
            self._deltas.append(
                TokenDelta([], finished=True, reason="error", error=message))
            self._finished = True
            self._cond.notify_all()

    def hand_back(self, message: str) -> None:
        """Worker side: terminal MIGRATING handback — the replica is
        retiring (drain, scale-down, rollout) and returns the unfinished
        stream to the door, which resumes it on a sibling from its journal
        (:meth:`next_delta` raises :class:`StreamMigratingError`). Every
        token delta pushed before this one is still delivered in order, so
        the door's committed-token journal is complete at handback."""
        with self._cond:
            if self._finished:
                return
            self._deltas.append(TokenDelta(
                [], finished=True, reason=REASON_MIGRATING, error=message))
            self._finished = True
            self._cond.notify_all()

    def cancel(self) -> None:
        """Consumer side: stop decoding for this sequence (client gone or
        the door gave up on a stalled stream). The scheduler evicts the
        slot at its next step."""
        with self._cond:
            self._cancelled = True
            self._cond.notify_all()

    @property
    def cancelled(self) -> bool:
        with self._cond:
            return self._cancelled

    @property
    def finished(self) -> bool:
        with self._cond:
            return self._finished and not self._deltas

    def next_delta(self, timeout: Optional[float] = None) -> TokenDelta:
        """Block for the next increment. Raises ``TimeoutError`` when no
        delta lands inside ``timeout`` (the door's stall detector — it
        converts this into a terminal error frame), ``GenerationError``
        when the stream already delivered its terminal error, and
        ``StopIteration`` once the terminal delta has been consumed."""
        with self._cond:
            if not self._deltas and self._finished:
                raise StopIteration
            if not self._deltas and not self._cond.wait_for(
                    lambda: bool(self._deltas), timeout):
                raise TimeoutError(
                    f"no token for sequence {self.seq_id} within "
                    f"{(timeout or 0.0):.1f}s")
            delta = self._deltas.pop(0)
            if delta.error is not None:
                if delta.reason == REASON_MIGRATING:
                    raise StreamMigratingError(delta.error)
                raise GenerationError(delta.error)
            return delta


class WorkerQueue:
    """A single inference worker's bounded inbox of pending queries.

    Each entry is (future, query, absolute-monotonic-deadline-or-None).
    ``max_depth`` bounds the inbox: a submit that would exceed it raises
    :class:`QueueFullError` (never blocks, never grows unbounded under a
    stalled worker). ``take_batch`` drops entries whose deadline already
    passed — their clients have stopped listening, so model time spent on
    them is pure overload amplification."""

    def __init__(self, max_depth: Optional[int] = None) -> None:
        self._lock = threading.Lock()
        self._cond = threading.Condition(self._lock)
        self._items: List[Tuple[QueryFuture, Any, Optional[float]]] = []
        self._closed = False
        #: None defers to RAFIKI_PREDICT_QUEUE_DEPTH at each submit (lazy:
        #: operators retune a live deployment; <=0 means uncapped)
        self._max_depth = max_depth
        self._expired = 0   # dropped by take_batch past their deadline
        self._rejected = 0  # refused by the depth cap
        # process-wide registry mirrors of the per-queue counters above
        # (/healthz keeps the per-queue ints; /metrics carries the
        # aggregate — same increment sites, so the two cannot drift)
        from rafiki_tpu.utils.metrics import REGISTRY

        self._m_expired = REGISTRY.counter(
            "rafiki_queue_expired_total",
            "queries dropped past their deadline in a worker queue")
        self._m_rejected = REGISTRY.counter(
            "rafiki_queue_rejected_total",
            "queries refused by a bounded worker queue's depth cap")

    def _cap(self) -> int:
        if self._max_depth is not None:
            return self._max_depth
        from rafiki_tpu import config

        return int(config.PREDICT_QUEUE_DEPTH)

    def depth(self) -> int:
        """Current inbox depth — the predictor's hedge suppression and the
        doors' wait estimation read this as the replica's load signal."""
        with self._lock:
            return len(self._items)

    def stats(self) -> Dict[str, int]:
        with self._lock:
            return {"depth": len(self._items), "expired": self._expired,
                    "rejected": self._rejected}

    def submit(self, query: Any,
               deadline: Optional[float] = None) -> QueryFuture:
        return self.submit_many([query], deadline=deadline)[0]

    def submit_many(self, queries: List[Any],
                    deadline: Optional[float] = None,
                    trace=None) -> List[QueryFuture]:
        """Enqueue a whole request's queries atomically (one lock, one
        wake-up). A per-query submit loop can lose a race with the worker:
        it wakes after the first item, serves a singleton batch, and the
        rest of the request waits a full dispatch behind it — with the
        batch deadline at 0 (serve immediately), atomic enqueue is what
        keeps one request one batch. ``deadline`` is the request's absolute
        ``time.monotonic()`` deadline; atomicity also means the depth cap
        admits or rejects the request as a unit (no half-enqueued
        requests). ``trace`` (a sampled request's RequestTrace) rides the
        futures so the worker records its spans into the door's tree."""
        with self._cond:
            if self._closed:
                futs = [QueryFuture() for _ in queries]
                for fut in futs:
                    fut.set_error(RuntimeError("worker queue closed"))
                return futs
            cap = self._cap()
            if cap > 0 and len(self._items) + len(queries) > cap:
                self._rejected += len(queries)
                self._m_rejected.inc(len(queries))
                raise QueueFullError(
                    f"worker queue full ({len(self._items)}/{cap} queued; "
                    f"refusing {len(queries)} more)")
            futs = [QueryFuture() for _ in queries]
            if trace is not None:
                trace.mark_submitted()
                for fut in futs:
                    fut.trace = trace
            self._items.extend(
                (fut, q, deadline) for fut, q in zip(futs, queries))
            self._cond.notify()
        return futs

    def _drain_fresh(  # guarded-by: _lock
        self, n: int, now: float,
        batch: List[Tuple[QueryFuture, Any]],
    ) -> None:
        """Move up to ``n`` unexpired entries into ``batch``; entries past
        their deadline resolve with TimeoutError instead of costing model
        time. Caller holds the lock."""
        while n > 0 and self._items:
            fut, query, deadline = self._items.pop(0)
            if deadline is not None and now >= deadline:
                self._expired += 1
                self._m_expired.inc()
                fut.set_error(TimeoutError(
                    "query expired in the worker queue before dispatch"))
                continue
            if fut.trace is not None:
                fut.trace.mark_dequeued(now)
            batch.append((fut, query))
            n -= 1

    def take_batch(
        self,
        max_size: int,
        deadline_s: float,
        wait_timeout_s: float = 0.5,
    ) -> Optional[List[Tuple[QueryFuture, Any]]]:
        """Block until work arrives (or `wait_timeout_s` elapses), then keep
        draining until the batch fills or `deadline_s` passes since the first
        item. Returns [] on timeout so callers can check stop flags, and
        None once the queue is CLOSED and drained — a closed queue answers
        instantly, so treating it like a timeout would turn the caller's
        poll loop into a busy spin. Entries whose request deadline has
        passed are dropped (futures resolved with TimeoutError), never
        returned; a take that drops everything returns [] like a timeout."""
        with self._cond:
            if not self._items and not self._closed:
                self._cond.wait(wait_timeout_s)
            if not self._items:
                return None if self._closed else []
            first_t = time.monotonic()
            batch: List[Tuple[QueryFuture, Any]] = []
            self._drain_fresh(max_size, first_t, batch)
            while len(batch) < max_size and not self._closed:
                remaining = deadline_s - (time.monotonic() - first_t)
                if remaining <= 0:
                    break
                if not self._items:
                    self._cond.wait(remaining)
                self._drain_fresh(
                    max_size - len(batch), time.monotonic(), batch)
            if not batch and not self._items and self._closed:
                return None
            return batch

    def close(self) -> None:
        with self._cond:
            self._closed = True
            for fut, _, _ in self._items:
                fut.set_error(RuntimeError("worker queue closed"))
            self._items.clear()
            self._cond.notify_all()


class Broker(abc.ABC):
    """Transport seam between predictors and inference workers."""

    @abc.abstractmethod
    def register_worker(self, inference_job_id: str, worker_id: str) -> WorkerQueue:
        ...

    @abc.abstractmethod
    def unregister_worker(self, inference_job_id: str, worker_id: str) -> None:
        ...

    @abc.abstractmethod
    def get_worker_queues(self, inference_job_id: str) -> Dict[str, WorkerQueue]:
        ...


class InProcessBroker(Broker):
    """Single-host broker: queues live in process memory."""

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._queues: Dict[str, Dict[str, WorkerQueue]] = {}

    def register_worker(self, inference_job_id: str, worker_id: str) -> WorkerQueue:
        with self._lock:
            q = WorkerQueue()
            self._queues.setdefault(inference_job_id, {})[worker_id] = q
            return q

    def unregister_worker(self, inference_job_id: str, worker_id: str) -> None:
        with self._lock:
            q = self._queues.get(inference_job_id, {}).pop(worker_id, None)
        if q is not None:
            q.close()

    def get_worker_queues(self, inference_job_id: str) -> Dict[str, WorkerQueue]:
        with self._lock:
            return dict(self._queues.get(inference_job_id, {}))
