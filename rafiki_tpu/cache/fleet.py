"""Cross-host serving data plane: remote worker queues over agent HTTP.

The reference placed inference workers on ANY swarm node and carried
queries to them through a central Redis (reference
rafiki/admin/services_manager.py:204-239, rafiki/cache/cache.py). Here the
local data plane is shm/condvar queues co-located with each host's
workers; what crosses hosts is one HTTP relay hop:

    predictor (admin host)
        └─ HttpWorkerQueue.submit(query) -> QueryFuture
             └─ sender thread coalesces pending queries into ONE
                POST /predict_relay/<job>/<worker> on the worker's host
                agent (placement/agent.py), which submits them to its
                local shm queue and answers when the worker resolves them.

The sender-side coalescing mirrors the worker's own continuous batching:
a burst of submits becomes one relay request, so the extra hop costs one
RTT per *batch*, not per query. ``FleetBroker`` composes these remote
queues with any local ``Broker`` behind the same seam, so the Predictor's
trial-grouped, hedged fan-out (predictor/predictor.py) works unchanged
across hosts.
"""

from __future__ import annotations

import logging
import threading
import time
from typing import Any, Dict, List, Optional, Tuple

from rafiki_tpu import config
from rafiki_tpu.cache import wire
from rafiki_tpu.cache.queue import Broker, QueryFuture, QueueFullError
from rafiki_tpu.utils.agent_http import (
    AgentHTTPError,
    AgentTransportError,
    call_agent,
)

logger = logging.getLogger(__name__)

# one relay request carries at most this many queries — bounds relay
# payloads while still letting a burst ride one RTT
RELAY_MAX_BATCH = 4 * config.PREDICT_MAX_BATCH_SIZE


class HttpWorkerQueue:
    """WorkerQueue-shaped client for an inference worker on a remote host.

    ``submit`` never blocks: the (future, query) pair lands in a pending
    list and a dedicated sender thread drains it — all pairs pending at
    drain time travel in one relay POST. Sequential relay calls per
    worker mirror the worker's own one-batch-at-a-time serve loop;
    replica concurrency comes from the predictor fanning out across
    workers, exactly as on the local path."""

    def __init__(self, agent_addr: str, inference_job_id: str,
                 worker_id: str, key: Optional[str] = None,
                 timeout_s: Optional[float] = None):
        self.agent_addr = agent_addr  # health subsystem evicts by host
        self._addr = agent_addr
        self._job_id = inference_job_id
        self._worker_id = worker_id
        self._key = key
        # the worker-side deadline travels WITH each relay request (the
        # agent would otherwise cap remote work at its own default while
        # local replicas honor this queue's SLO); the transport waits 5 s
        # longer so the worker's answer or error wins the race, not the
        # socket. Note: per-request SLOs passed to Predictor.predict are
        # enforced admin-side via future.result() on both paths; the
        # worker-side budget for a remote replica is this queue-level
        # setting, config.PREDICT_TIMEOUT_S by default.
        self._worker_timeout_s = (timeout_s if timeout_s is not None
                                  else config.PREDICT_TIMEOUT_S)
        self._timeout_s = self._worker_timeout_s + 5.0
        # binary wire negotiation (cache/wire.py): None = not yet probed.
        # The agent advertises its supported codec versions on /healthz;
        # a peer that doesn't (old version, probe failure) gets JSON
        # framing — interop is the default, the binary hop is earned.
        self._wire_ok: Optional[bool] = None
        self._cond = threading.Condition()
        self._pending: List[Tuple[QueryFuture, Any, Optional[float]]] = []
        self._inflight = 0  # queries inside the current relay round-trip
        self._expired = 0
        self._rejected = 0
        # registry mirrors of the relay queue's shed counters — same
        # process-wide aggregates the local WorkerQueue feeds
        from rafiki_tpu.utils.metrics import REGISTRY

        self._m_expired = REGISTRY.counter(
            "rafiki_queue_expired_total",
            "queries dropped past their deadline in a worker queue")
        self._m_rejected = REGISTRY.counter(
            "rafiki_queue_rejected_total",
            "queries refused by a bounded worker queue's depth cap")
        self._closed = False
        self._thread = threading.Thread(
            target=self._sender, daemon=True,
            name=f"relay-{worker_id[:8]}@{agent_addr}")
        self._thread.start()

    def depth(self) -> int:
        """Load signal for hedge suppression / wait estimation: queries
        waiting for the sender PLUS queries riding the current relay RTT
        (the remote worker is busy with those — they are its queue)."""
        with self._cond:
            return len(self._pending) + self._inflight

    def stats(self) -> Dict[str, int]:
        with self._cond:
            return {"depth": len(self._pending) + self._inflight,
                    "expired": self._expired, "rejected": self._rejected}

    def submit(self, query: Any,
               deadline: Optional[float] = None) -> QueryFuture:
        return self.submit_many([query], deadline=deadline)[0]

    def submit_many(self, queries: List[Any],
                    deadline: Optional[float] = None,
                    trace=None) -> List[QueryFuture]:
        """Atomic enqueue of one request's queries (one lock, one wake-up)
        so the sender relays them as one HTTP batch instead of racing the
        sender thread into a singleton first batch. Bounded exactly like
        the local WorkerQueue (RAFIKI_PREDICT_QUEUE_DEPTH counts pending +
        in-flight): a stalled host must shed here, admin-side, not grow an
        unbounded relay backlog. A sampled request's ``trace`` rides its
        futures; the sender forwards the context in the relay body and
        grafts the remote spans back (placement/agent.py answers
        ``trace_spans``)."""
        with self._cond:
            if self._closed:
                futs = [QueryFuture() for _ in queries]
                for fut in futs:
                    fut.set_error(RuntimeError("remote worker queue closed"))
                return futs
            cap = int(config.PREDICT_QUEUE_DEPTH)
            queued = len(self._pending) + self._inflight
            if cap > 0 and queued + len(queries) > cap:
                self._rejected += len(queries)
                self._m_rejected.inc(len(queries))
                raise QueueFullError(
                    f"relay queue to {self._addr} full ({queued}/{cap})")
            futs = [QueryFuture() for _ in queries]
            if trace is not None:
                trace.mark_submitted()
                for fut in futs:
                    fut.trace = trace
            self._pending.extend(
                (fut, q, deadline) for fut, q in zip(futs, queries))
            self._cond.notify()
        return futs

    def _sender(self) -> None:
        while True:
            with self._cond:
                while not self._pending and not self._closed:
                    self._cond.wait()
                if self._closed:
                    # close() already failed every pending future; relaying
                    # a popped batch after close would block teardown on a
                    # full transport timeout
                    return
                now = time.monotonic()
                batch = []
                while (len(batch) < RELAY_MAX_BATCH and self._pending):
                    fut, q, dl = self._pending.pop(0)
                    if dl is not None and now >= dl:
                        # expired while waiting for the sender: don't spend
                        # a relay slot (and remote model time) on it
                        self._expired += 1
                        self._m_expired.inc()
                        fut.set_error(TimeoutError(
                            "query expired in the relay queue before send"))
                        continue
                    batch.append((fut, q))
                self._inflight = len(batch)
            if not batch:
                continue
            futures = [f for f, _ in batch]
            # one relay call may coalesce several requests; at most ONE
            # trace context rides it (the first sampled entry's — hop
            # tracing is a sampling of the flow, not an audit log)
            trace = next((f.trace for f in futures
                          if getattr(f, "trace", None) is not None), None)
            try:
                preds = self._relay([q for _, q in batch], trace=trace)
                if len(preds) != len(futures):
                    raise RuntimeError(
                        f"relay returned {len(preds)} predictions for "
                        f"{len(futures)} queries")
                for fut, pred in zip(futures, preds):
                    fut.set_result(pred)
            # lint: absorb(the error reaches every waiter via fut.set_error)
            except Exception as e:
                for fut in futures:
                    fut.set_error(e)
            finally:
                with self._cond:
                    self._inflight = 0

    def _wire_supported(self) -> bool:
        """One lazy /healthz probe decides whether this relay may ship
        binary wire frames; unknown/unreachable peers stay on JSON and
        the probe retries on a later relay (the flag is only cached once
        an answer arrives). Any overlap with our SUPPORTED_VERSIONS
        qualifies — traceless relay frames are emitted as v1, so a v1-only
        peer (pre-trace build) keeps its binary hop."""
        if not wire.binary_enabled():
            return False
        if self._wire_ok is None:
            try:
                h = call_agent(self._addr, "GET", "/healthz",
                               timeout_s=min(self._timeout_s, 5.0))
                advertised = set(h.get("wire_versions") or [])
                self._wire_ok = bool(advertised & wire.SUPPORTED_VERSIONS)
            # lint: absorb(unprobeable peer falls back to JSON until the next probe)
            except Exception:
                return False
        return bool(self._wire_ok)

    def _relay(self, queries: List[Any], trace=None) -> List[Any]:
        binary = self._wire_supported()
        q_payload: Any = queries
        if binary:
            # homogeneous ndarray queries travel as ONE stacked array
            # (single raw-bytes header entry instead of per-row JSON)
            stacked = wire.stack_batch(queries)
            if stacked is not None:
                q_payload = stacked
        body = {"queries": q_payload, "timeout_s": self._worker_timeout_s}
        if trace is not None:
            # the context rides the BODY (plain JSON-able dict), not the
            # frame header — an old agent ignores the unknown key and
            # still serves the relay, the mixed-version contract
            body["trace"] = trace.ctx.to_wire()
        try:
            out = call_agent(
                self._addr, "POST",
                f"/predict_relay/{self._job_id}/{self._worker_id}",
                body=body,
                key=self._key, timeout_s=self._timeout_s,
                wire_frames=binary)
            if trace is not None and isinstance(out, dict) \
                    and out.get("trace_spans") is not None:
                # remote offsets are relative to the AGENT's submit time;
                # re-anchoring at our submit folds the relay transit into
                # the first remote span's offset — same host-order, ~one
                # RTT of skew, fine for a latency breakdown
                trace.add_wire_spans(out["trace_spans"],
                                     anchor=trace.t_submit)
            return list(out["predictions"])
        except AgentHTTPError as e:
            raise RuntimeError(f"relay {self._addr}: {e.message}") from None
        except AgentTransportError as e:
            raise RuntimeError(f"relay unreachable: {e}") from None

    def close(self, join_timeout_s: float = 1.0) -> None:
        """Fail all pending work and stop the sender thread. The closed
        flag short-circuits the sender's next loop iteration; the bounded
        join makes broker teardown deterministic in tests. An in-flight
        relay can still hold the (daemon) thread for up to its transport
        timeout — we never wait that out, and the join is kept short so
        wait=False teardown paths stay snappy even mid-relay."""
        with self._cond:
            self._closed = True
            for fut, _, _ in self._pending:
                fut.set_error(RuntimeError("remote worker queue closed"))
            self._pending.clear()
            self._cond.notify_all()
        if self._thread is not threading.current_thread():
            self._thread.join(timeout=join_timeout_s)


class FleetBroker(Broker):
    """Compose a host-local broker with remote agent-relayed queues.

    Local workers register/unregister through the wrapped base broker
    exactly as before; the placement layer registers REMOTE workers here
    when it places an inference executor on a host agent
    (placement/hosts.py). ``get_worker_queues`` merges both, so the
    Predictor is host-agnostic."""

    def __init__(self, base: Broker):
        self._base = base
        self._lock = threading.Lock()
        self._remote: Dict[str, Dict[str, HttpWorkerQueue]] = {}

    # pass-throughs for co-located workers -------------------------------
    def register_worker(self, inference_job_id: str, worker_id: str):
        return self._base.register_worker(inference_job_id, worker_id)

    def unregister_worker(self, inference_job_id: str, worker_id: str) -> None:
        with self._lock:
            q = self._remote.get(inference_job_id, {}).pop(worker_id, None)
        if q is not None:
            q.close()
            return
        self._base.unregister_worker(inference_job_id, worker_id)

    def get_worker_queues(self, inference_job_id: str) -> Dict[str, Any]:
        out: Dict[str, Any] = dict(
            self._base.get_worker_queues(inference_job_id))
        with self._lock:
            out.update(self._remote.get(inference_job_id, {}))
        return out

    # remote registration (placement/hosts.py) ---------------------------
    def register_remote_worker(
        self, inference_job_id: str, worker_id: str, agent_addr: str,
        key: Optional[str] = None,
    ) -> HttpWorkerQueue:
        q = HttpWorkerQueue(agent_addr, inference_job_id, worker_id, key=key)
        with self._lock:
            old = self._remote.setdefault(
                inference_job_id, {}).get(worker_id)
            self._remote[inference_job_id][worker_id] = q
        if old is not None:
            old.close()
        return q

    # fleet health (placement/hosts.py heartbeat monitor) ----------------
    def evict_agent(self, agent_addr: str) -> List[Tuple[str, str]]:
        """Drop and close every remote queue relayed through ``agent_addr``
        (a host marked DOWN). Returns the evicted (job_id, worker_id)
        pairs. Without this, the predictor's hedged fan-out keeps burning
        deadline slices on replicas that can never answer."""
        evicted: List[Tuple[str, HttpWorkerQueue]] = []
        with self._lock:
            for job_id, queues in self._remote.items():
                for wid, q in list(queues.items()):
                    if q.agent_addr == agent_addr:
                        queues.pop(wid)
                        evicted.append(((job_id, wid), q))
        for _, q in evicted:
            q.close(join_timeout_s=0.0)  # dead host: don't wait on its relay
        return [pair for pair, _ in evicted]

    # optional base-broker capabilities ----------------------------------
    @property
    def prefix(self):
        # process placement needs the shm namespace of the underlying
        # broker (placement/process.py); None — not AttributeError —
        # when the base broker has no shm namespace, so callers can
        # decide explicitly
        return getattr(self._base, "prefix", None)

    def close(self) -> None:
        with self._lock:
            remote, self._remote = self._remote, {}
        for queues in remote.values():
            for q in queues.values():
                q.close()
        if hasattr(self._base, "close"):
            self._base.close()
