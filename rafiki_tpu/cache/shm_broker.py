"""Cross-process serving broker over the native shared-memory queue.

`InProcessBroker` (cache/queue.py) hands queries between threads of one
process. This broker carries the same traffic between *processes* on one
host through rafiki_tpu.native.shm_queue — the native replacement for the
reference's Redis data plane (reference rafiki/cache/cache.py: every query
rpush'd over TCP to a Redis server and polled at 0.25 s). Queue names are
deterministic in (prefix, job, worker), so a worker process can attach with
`ShmWorkerQueue.attach(...)` knowing only its ids.

Wire format: JSON messages {"id": ..., "query": ...} on the per-worker
query queue; {"id": ..., "result": ...} | {"id": ..., "error": ...} on the
per-job response queue. A listener thread on the predictor side resolves
`QueryFuture`s by id.

Select with RAFIKI_BROKER=shm (Admin falls back to the in-process broker if
the native library can't be built).
"""

from __future__ import annotations

import hashlib
import json
import logging
import threading
import time
import uuid
from typing import Any, Dict, List, Optional, Tuple

from rafiki_tpu.cache.queue import Broker, QueryFuture, QueueFullError
from rafiki_tpu.native.shm_queue import (
    ShmMessageQueue,
    ShmQueueClosed,
    available,
)

logger = logging.getLogger(__name__)


def _qname(prefix: str, *parts: str) -> str:
    digest = hashlib.sha256("|".join(parts).encode()).hexdigest()[:24]
    return f"/{prefix}-{digest}"


def _json_dumps(obj: Any) -> bytes:
    """Shm wire format is JSON (it crosses process boundaries), which is
    narrower than InProcessBroker's arbitrary-object handoff. The shared
    wire convention (utils/jsonutil.py) converts numpy arrays/scalars at
    any depth; anything else non-JSON raises TypeError."""
    from rafiki_tpu.utils.jsonutil import dumps

    return dumps(obj).encode()


class ShmWorkerQueue:
    """Worker-side view: drains query batches, pushes responses.

    Duck-types cache.queue.WorkerQueue's `take_batch` but yields
    (ResponseHandle, query) pairs — the handle writes the response message
    instead of resolving an in-process future.
    """

    class ResponseHandle:
        __slots__ = ("_rq", "_id")

        def __init__(self, rq: ShmMessageQueue, qid: str):
            self._rq = rq
            self._id = qid

        def set_result(self, value: Any) -> None:
            # transport backpressure (full response ring, broker mid-close)
            # must not crash the serving worker loop — the predictor's SLO
            # timeout covers the dropped response
            try:
                self._rq.push(_json_dumps({"id": self._id, "result": value}))
            except Exception:
                logger.exception("dropping response %s", self._id)

        def set_error(self, error: BaseException) -> None:
            try:
                self._rq.push(_json_dumps(
                    {"id": self._id, "error": str(error)}))
            except Exception:
                logger.exception("dropping error response %s", self._id)

    def __init__(self, query_q: ShmMessageQueue, response_q: ShmMessageQueue):
        self._qq = query_q
        self._rq = response_q

    @classmethod
    def attach(cls, prefix: str, inference_job_id: str,
               worker_id: str) -> "ShmWorkerQueue":
        """Open the queues from another process by deterministic name."""
        qq = ShmMessageQueue(
            _qname(prefix, "q", inference_job_id, worker_id), create=False)
        rq = ShmMessageQueue(
            _qname(prefix, "r", inference_job_id), create=False)
        return cls(qq, rq)

    def take_batch(self, max_size: int, deadline_s: float,
                   wait_timeout_s: float = 0.5
                   ) -> Optional[List[Tuple["ShmWorkerQueue.ResponseHandle",
                                            Any]]]:
        """[] on timeout; None once the queue is closed-and-drained (same
        contract as cache.queue.WorkerQueue.take_batch — a closed ring
        answers instantly, and callers polling it as if it were a timeout
        would spin hot)."""
        try:
            first = self._qq.pop(timeout_s=wait_timeout_s)
        except ShmQueueClosed:
            return None
        if first is None:
            return []
        batch = [first]
        t0 = time.monotonic()
        while len(batch) < max_size:
            # drain whatever is ALREADY in the ring without waiting — same
            # contract as WorkerQueue.take_batch (the deadline is only an
            # optional coalescing wait, and at the default 0 a multi-query
            # request pushed as consecutive messages must still come out
            # as one batch)
            try:
                nxt = self._qq.pop(timeout_s=0)
                if nxt is None:
                    remaining = deadline_s - (time.monotonic() - t0)
                    if remaining <= 0:
                        break
                    nxt = self._qq.pop(timeout_s=remaining)
            except ShmQueueClosed:
                break
            if nxt is None:
                break
            batch.append(nxt)
        out = []
        now = time.monotonic()
        for raw in batch:
            msg = json.loads(raw)
            handle = self.ResponseHandle(self._rq, msg["id"])
            # overload control: a query whose request deadline passed while
            # it sat in the ring is dropped here, not served — CLOCK_MONOTONIC
            # is system-wide on one host, so the submitter's absolute
            # deadline is directly comparable in this worker process
            deadline = msg.get("deadline")
            if deadline is not None and now >= float(deadline):
                handle.set_error(TimeoutError(
                    "query expired in the shm queue before dispatch"))
                continue
            out.append((handle, msg["query"]))
        return out

    def close(self) -> None:
        self._qq.close()


class _SubmitProxy:
    """Predictor-side view of one worker's query queue.

    Overload control happens owner-side (this process): the broker counts
    each worker's *outstanding* queries (submitted, not yet answered), so
    ``depth()`` gives the hedge-suppression/admission load signal and
    ``submit_many`` enforces RAFIKI_PREDICT_QUEUE_DEPTH with the same
    QueueFullError contract as the in-process queue — the shm ring itself
    cannot be asked its message count from here."""

    def __init__(self, broker: "ShmBroker", job_id: str, worker_id: str,
                 query_q: ShmMessageQueue):
        self._broker = broker
        self._job_id = job_id
        self._worker_id = worker_id
        self._qq = query_q

    def depth(self) -> int:
        return self._broker._outstanding_count(self._job_id, self._worker_id)

    def submit(self, query: Any,
               deadline: Optional[float] = None) -> QueryFuture:
        return self.submit_many([query], deadline=deadline)[0]

    def submit_many(self, queries: List[Any],
                    deadline: Optional[float] = None) -> List[QueryFuture]:
        # cross-process ring: one message per query; the ring preserves
        # push order and the worker-side take_batch drains every
        # already-queued message before it considers the deadline, so
        # consecutive pushes land as one batch without in-process-style
        # lock atomicity. The depth-cap check is all-or-nothing per
        # request, like WorkerQueue.submit_many, and the reservation is
        # atomic with it (released on response, push failure, or expiry).
        self._broker._reserve_capacity(
            self._job_id, self._worker_id, len(queries))
        out = []
        for query in queries:
            qid = uuid.uuid4().hex
            fut = QueryFuture()
            self._broker._register_pending(
                self._job_id, self._worker_id, qid, fut, deadline)
            msg = {"id": qid, "query": query}
            if deadline is not None:
                # absolute monotonic deadline; comparable worker-side
                # because both processes share the host's CLOCK_MONOTONIC
                msg["deadline"] = deadline
            try:
                self._qq.push(_json_dumps(msg))
            except Exception as e:
                self._broker._pop_pending(self._job_id, qid)
                fut.set_error(e)
            out.append(fut)
        return out


class ShmBroker(Broker):
    """Owner (predictor-process) side of the shm data plane."""

    def __init__(self, prefix: Optional[str] = None,
                 queue_capacity: int = 1 << 20):
        if not available():
            raise RuntimeError("native shmqueue unavailable")
        self.prefix = prefix or f"rafiki{uuid.uuid4().hex[:8]}"
        self._capacity = queue_capacity
        self._lock = threading.Lock()
        self._query_qs: Dict[str, Dict[str, ShmMessageQueue]] = {}
        self._response_qs: Dict[str, ShmMessageQueue] = {}
        # qid -> (future, worker_id, expiry_ts): worker_id feeds the
        # per-worker outstanding counts (the depth signal), expiry_ts lets
        # a never-answered query (worker crashed mid-batch) be pruned
        # instead of counting against the depth cap forever
        self._pending: Dict[str, Dict[str, Tuple[QueryFuture, str, float]]] = {}
        self._outstanding: Dict[Tuple[str, str], int] = {}
        self._listeners: Dict[str, threading.Thread] = {}
        self._graveyard: List[ShmMessageQueue] = []
        self._closed = False

    # -- Broker interface --------------------------------------------------

    def register_worker(self, inference_job_id: str,
                        worker_id: str) -> ShmWorkerQueue:
        with self._lock:
            rq = self._ensure_response_queue(inference_job_id)
            qq = ShmMessageQueue(
                _qname(self.prefix, "q", inference_job_id, worker_id),
                capacity=self._capacity, create=True)
            self._query_qs.setdefault(inference_job_id, {})[worker_id] = qq
        # a same-process worker thread shares the owner's handles; a separate
        # worker process uses ShmWorkerQueue.attach() instead
        return ShmWorkerQueue(qq, rq)

    def unregister_worker(self, inference_job_id: str, worker_id: str) -> None:
        with self._lock:
            qq = self._query_qs.get(inference_job_id, {}).pop(worker_id, None)
            if qq is not None:
                # close only — a _SubmitProxy snapshot taken before this call
                # may still hold the handle, and destroy() munmaps under it
                # (closed pushes fail cleanly; unmapped ones segfault).
                # The segment is reclaimed at broker close().
                qq.close()
                self._graveyard.append(qq)

    def get_worker_queues(self, inference_job_id: str) -> Dict[str, Any]:
        with self._lock:
            return {
                wid: _SubmitProxy(self, inference_job_id, wid, qq)
                for wid, qq in self._query_qs.get(inference_job_id, {}).items()
            }

    # -- response plumbing -------------------------------------------------

    def _ensure_response_queue(self, job_id: str) -> ShmMessageQueue:
        """Caller holds self._lock."""
        if job_id not in self._response_qs:
            rq = ShmMessageQueue(
                _qname(self.prefix, "r", job_id),
                capacity=self._capacity, create=True)
            self._response_qs[job_id] = rq
            self._pending[job_id] = {}
            t = threading.Thread(
                target=self._listen, args=(job_id, rq),
                name=f"shm-listener-{job_id[:8]}", daemon=True)
            self._listeners[job_id] = t
            t.start()
        return self._response_qs[job_id]

    def _register_pending(self, job_id: str, worker_id: str, qid: str,
                          fut: QueryFuture,
                          deadline: Optional[float]) -> None:
        """Record one reserved query's future (the outstanding count was
        already taken by _reserve_capacity — registering must NOT count
        again). Expiry gets a grace period past the request deadline (or
        the configured SLO): a query the worker never answers must stop
        counting against its depth eventually, or one crash would pin the
        replica "full" forever."""
        from rafiki_tpu import config

        expiry = (deadline if deadline is not None
                  else time.monotonic() + config.PREDICT_TIMEOUT_S) + 30.0
        with self._lock:
            self._pending.setdefault(job_id, {})[qid] = (
                fut, worker_id, expiry)

    def _pop_pending(self, job_id: str, qid: str) -> Optional[QueryFuture]:
        with self._lock:
            entry = self._pending.get(job_id, {}).pop(qid, None)
            if entry is None:
                return None
            fut, worker_id, _ = entry
            self._dec_outstanding_locked(job_id, worker_id)
            return fut

    def _dec_outstanding_locked(self, job_id: str, worker_id: str) -> None:
        key = (job_id, worker_id)
        n = self._outstanding.get(key, 0) - 1
        if n <= 0:
            self._outstanding.pop(key, None)
        else:
            self._outstanding[key] = n

    def _prune_expired_locked(self, job_id: str, worker_id: str) -> None:
        """Drop never-answered entries past their expiry (worker crashed
        mid-batch). Must run on EVERY read of the count, not just on
        submits: the admission layer sheds on depth() *before* any submit
        happens, so a prune that only ran at submit time could never fire
        again once phantoms pushed the estimated wait over every
        deadline — a permanent-429 lockout."""
        now = time.monotonic()
        job_pending = self._pending.get(job_id, {})
        for qid, (_, wid, expiry) in list(job_pending.items()):
            if wid == worker_id and now >= expiry:
                job_pending.pop(qid)
                self._dec_outstanding_locked(job_id, wid)

    def _outstanding_count(self, job_id: str, worker_id: str) -> int:
        with self._lock:
            if self._outstanding.get((job_id, worker_id), 0) > 0:
                self._prune_expired_locked(job_id, worker_id)
            return self._outstanding.get((job_id, worker_id), 0)

    def _reserve_capacity(self, job_id: str, worker_id: str, n: int) -> None:
        """Atomically check RAFIKI_PREDICT_QUEUE_DEPTH and claim ``n``
        outstanding slots (one lock hold: a check-then-register split
        would let concurrent submitters jointly overshoot the cap). The
        claim is released by _pop_pending (response/push-failure) or by
        expiry pruning."""
        from rafiki_tpu import config

        cap = int(config.PREDICT_QUEUE_DEPTH)
        key = (job_id, worker_id)
        with self._lock:
            if self._outstanding.get(key, 0) > 0:
                self._prune_expired_locked(job_id, worker_id)
            queued = self._outstanding.get(key, 0)
            if cap > 0 and queued + n > cap:
                raise QueueFullError(
                    f"shm worker {worker_id} full "
                    f"({queued}/{cap} outstanding)")
            self._outstanding[key] = queued + n

    def _listen(self, job_id: str, rq: ShmMessageQueue) -> None:
        while not self._closed:
            try:
                raw = rq.pop(timeout_s=0.5)
            except ShmQueueClosed:
                break
            except Exception:
                logger.exception("response listener %s died", job_id)
                break
            if raw is None:
                continue
            try:
                msg = json.loads(raw)
            except json.JSONDecodeError:
                logger.error("bad response message on %s", job_id)
                continue
            fut = self._pop_pending(job_id, msg.get("id", ""))
            if fut is None:
                continue
            if "error" in msg:
                fut.set_error(RuntimeError(msg["error"]))
            else:
                fut.set_result(msg.get("result"))

    # -- lifecycle ---------------------------------------------------------

    def close(self) -> None:
        self._closed = True
        with self._lock:
            jobs = list(self._query_qs)
            for job_id in jobs:
                for qq in self._query_qs[job_id].values():
                    qq.close()
                    qq.destroy()
            self._query_qs.clear()
            for qq in self._graveyard:
                qq.destroy()
            self._graveyard.clear()
            for rq in self._response_qs.values():
                rq.close()
        for t in self._listeners.values():
            t.join(timeout=2.0)
        with self._lock:
            for rq in self._response_qs.values():
                rq.destroy()
            self._response_qs.clear()
            for pend in self._pending.values():
                for fut, _, _ in pend.values():
                    fut.set_error(RuntimeError("broker closed"))
            self._pending.clear()
            self._outstanding.clear()


class ShmBrokerClient:
    """Worker-process side of the shm data plane.

    The owner (`ShmBroker`, in the admin/predictor process) creates the
    segments when a serving service is placed; a worker process built by
    ProcessPlacementManager attaches to them by deterministic name —
    the analogue of the reference's workers connecting to the Redis address
    passed in their container env (reference rafiki/cache/cache.py:21,
    services_manager env plumbing). `register_worker` therefore *attaches*
    (with retry, the owner may still be creating) and `unregister_worker`
    detaches without closing: segment lifecycle belongs to the owner, so a
    crashed-and-restarted worker can re-attach and resume serving.
    """

    def __init__(self, prefix: str, attach_timeout_s: float = 10.0):
        self.prefix = prefix
        self._attach_timeout_s = attach_timeout_s
        self._queues: Dict[Tuple[str, str], ShmWorkerQueue] = {}

    def register_worker(self, inference_job_id: str,
                        worker_id: str) -> ShmWorkerQueue:
        deadline = time.monotonic() + self._attach_timeout_s
        while True:
            try:
                wq = ShmWorkerQueue.attach(
                    self.prefix, inference_job_id, worker_id)
                break
            except Exception:
                if time.monotonic() >= deadline:
                    raise
                time.sleep(0.05)
        self._queues[(inference_job_id, worker_id)] = wq
        return wq

    def unregister_worker(self, inference_job_id: str, worker_id: str) -> None:
        wq = self._queues.pop((inference_job_id, worker_id), None)
        if wq is not None:
            # detach only (munmap, no shm_unlink — we are not the owner);
            # do NOT close: the shared closed flag would kill the segment
            # for the owner and for any restarted worker
            wq._qq.destroy()
            wq._rq.destroy()

    def get_worker_queues(self, inference_job_id: str) -> Dict[str, Any]:
        raise NotImplementedError(
            "worker-side broker client cannot enumerate queues; the "
            "predictor runs in the owner process")


def make_broker() -> Broker:
    """RAFIKI_BROKER=shm -> native cross-process broker (with fallback);
    anything else -> in-process condition-variable broker."""
    import os

    from rafiki_tpu.cache.queue import InProcessBroker

    if os.environ.get("RAFIKI_BROKER") == "shm":
        try:
            return ShmBroker()
        except Exception:
            logger.warning("shm broker unavailable; using in-process broker")
    return InProcessBroker()
