"""Cross-process serving broker over the native shared-memory queue.

`InProcessBroker` (cache/queue.py) hands queries between threads of one
process. This broker carries the same traffic between *processes* on one
host through rafiki_tpu.native.shm_queue — the native replacement for the
reference's Redis data plane (reference rafiki/cache/cache.py: every query
rpush'd over TCP to a Redis server and polled at 0.25 s). Queue names are
deterministic in (prefix, job, worker), so a worker process can attach with
`ShmWorkerQueue.attach(...)` knowing only its ids.

Wire format (cache/wire.py): one **binary frame per request** each way —
``{"ids": [...], "qarr": <stacked ndarray> | "queries": [...],
"deadline": ...}`` on the per-worker query queue, ``{"ids": [...],
"results": [...], "errors": {...}}`` on the per-job response queue —
ndarrays as raw bytes, decoded worker-side with zero-copy
``np.frombuffer`` views. The float→text→float tax of the old per-query
JSON messages was the serving path's dominant CPU cost (BENCH_r05), not
the model. Receivers *sniff* every popped message (binary magic vs JSON),
so legacy per-query JSON peers interoperate; responses echo the format
their query frame arrived in, and ``RAFIKI_WIRE_BINARY=0`` forces JSON
framing on the submit side for a version-mismatched fleet. A listener
thread on the predictor side resolves `QueryFuture`s by id.

Select with RAFIKI_BROKER=shm (Admin falls back to the in-process broker if
the native library can't be built).
"""

from __future__ import annotations

import hashlib
import json
import logging
import threading
import time
import uuid
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

from rafiki_tpu.cache import wire
from rafiki_tpu.cache.queue import (
    Broker,
    FrameTooLargeError,
    QueryFuture,
    QueueFullError,
)
from rafiki_tpu.native.shm_queue import (
    ShmMessageQueue,
    ShmQueueClosed,
    available,
)
from rafiki_tpu.utils import chaos
from rafiki_tpu.utils.jsonutil import json_default

logger = logging.getLogger(__name__)


def _qname(prefix: str, *parts: str) -> str:
    digest = hashlib.sha256("|".join(parts).encode()).hexdigest()[:24]
    return f"/{prefix}-{digest}"


def _encode_query_frame(ids: List[str], queries: List[Any],
                        deadline: Optional[float],
                        trace_meta: Optional[Dict[str, Any]] = None) -> bytes:
    """One frame for a whole submit_many request (binary unless
    RAFIKI_WIRE_BINARY=0). Homogeneous ndarray queries stack into ONE
    contiguous array (single header entry, single memcpy) — the common
    shape for the binary HTTP door, whose ``list(arr)`` rows share dtype
    and shape by construction. ``trace_meta`` (a sampled request's wire
    context + submit timestamp) rides the v2 frame header; under JSON
    framing it rides the message's ``_trace`` key instead so the
    kill-switch path keeps its traces too."""
    msg: Dict[str, Any] = {"ids": ids}
    if deadline is not None:
        msg["deadline"] = deadline
    # qarr only when the frame is actually binary: under JSON framing
    # (RAFIKI_WIRE_BINARY=0) a stacked array would serialize as nested
    # lists, which the receiving decoder must not confuse with rows
    stacked = wire.stack_batch(queries) if wire.binary_enabled() else None
    if stacked is not None:
        msg["qarr"] = stacked
    else:
        msg["queries"] = queries
        if trace_meta is not None and not wire.binary_enabled():
            msg["_trace"] = trace_meta
    return wire.dumps(msg, trace=trace_meta)


def _decode_query_frame(raw: bytes) -> Tuple[
        List[Tuple[str, Any, Optional[float]]], bool,
        Optional[Dict[str, Any]]]:
    """One popped query message -> ([(qid, query, deadline), ...],
    arrived_binary, trace_meta_or_None). Accepts the batched binary
    frame (v1 or trace-carrying v2), the batched JSON frame
    (RAFIKI_WIRE_BINARY=0 submitter), and the legacy per-query
    ``{"id", "query"}`` message. Raises WireFormatError on garbage."""
    binary = wire.is_frame(raw)
    msg, meta = wire.decode_any_meta(raw)
    if not isinstance(msg, dict):
        raise wire.WireFormatError("query frame is not an object")
    trace_meta = meta.get("trace") or msg.get("_trace")
    if not isinstance(trace_meta, dict):
        trace_meta = None
    try:
        # the frame decoded, but every field is still untrusted input:
        # ids must be strings (dict keys downstream) and the deadline a
        # number (compared against time.monotonic()) — anything else is
        # a malformed frame, absorbed by the caller, never a crash in
        # the worker serve loop
        deadline = msg.get("deadline")
        if deadline is not None:
            deadline = float(deadline)
        if "id" in msg:  # legacy single-query message
            if not isinstance(msg["id"], str):
                raise wire.WireFormatError("query id is not a string")
            return [(msg["id"], msg["query"], deadline)], binary, trace_meta
        ids = msg["ids"]
        if (not isinstance(ids, list)
                or not all(isinstance(i, str) for i in ids)):
            raise wire.WireFormatError("ids must be a list of strings")
        if "qarr" in msg:
            qarr = msg["qarr"]
            if isinstance(qarr, np.ndarray) and qarr.ndim >= 1:
                queries: List[Any] = list(qarr)  # zero-copy row views
            elif isinstance(qarr, list):
                # a JSON-framed qarr (old sender under the kill-switch)
                # arrives as nested lists: rows stay rows
                queries = qarr
            else:
                raise wire.WireFormatError("qarr is not a batch")
        else:
            queries = msg["queries"]
        if not isinstance(queries, (list, np.ndarray)) \
                or len(queries) != len(ids):
            raise wire.WireFormatError("queries/ids length mismatch")
        return ([(qid, q, deadline) for qid, q in zip(ids, queries)],
                binary, trace_meta)
    except (KeyError, TypeError, ValueError) as e:
        if isinstance(e, wire.WireFormatError):
            raise
        raise wire.WireFormatError(f"malformed query frame: {e}") from e


class _FrameResponder:
    """Accumulates one popped query frame's responses and flushes them as
    ONE message in the same wire format the frame arrived in (binary
    frame -> batched binary response; legacy JSON -> legacy per-id JSON
    messages, so an old-version listener still resolves them).

    Flush fires when every id has resolved — the worker loop always
    resolves a batch completely (results, a shared error, or take-time
    expiry), so a response frame is written exactly once per request.
    Transport backpressure (full response ring, broker mid-close) must
    not crash the serving worker loop — the predictor's SLO timeout
    covers a dropped response frame.

    For a SAMPLED request (the query frame carried trace metadata) the
    responder also collects worker-side spans — queue_wait, codec_decode,
    batch_assembly, model_forward — as ``[name, offset_s, duration_s]``
    triples relative to the submitter's ``ts`` and ships them home in the
    response frame's metadata, where the broker listener grafts them onto
    the door's span tree. Legacy JSON responses drop the spans (old
    listeners can't read them) but still serve the request."""

    __slots__ = ("_rq", "_ids", "_binary", "_lock", "_out",
                 "trace_meta", "_spans")

    def __init__(self, rq: ShmMessageQueue, ids: List[str], binary: bool,
                 trace_meta: Optional[Dict[str, Any]] = None):
        self._rq = rq
        self._ids = ids
        self._binary = binary
        self._lock = threading.Lock()
        self._out: Dict[str, Tuple[str, Any]] = {}
        self.trace_meta = trace_meta if (
            isinstance(trace_meta, dict) and trace_meta.get("s")) else None
        self._spans: List[List[Any]] = []

    @property
    def anchor(self) -> Optional[float]:
        """The submitter's monotonic submit timestamp (same host, same
        CLOCK_MONOTONIC) — worker span offsets are measured against it."""
        if self.trace_meta is None:
            return None
        try:
            return float(self.trace_meta.get("ts"))
        except (TypeError, ValueError):
            return None

    def add_span(self, name: str, start: float, end: float) -> None:
        """Record one worker-side span (monotonic interval). No-op for
        unsampled frames so the hot path pays one None check."""
        anchor = self.anchor
        if anchor is None:
            return
        with self._lock:
            self._spans.append(
                [name, round(start - anchor, 6),
                 round(max(end - start, 0.0), 6)])

    def resolve(self, qid: str, kind: str, value: Any) -> None:
        with self._lock:
            if qid in self._out:
                return  # first resolution wins (double-set guard)
            self._out[qid] = (kind, value)
            if len(self._out) < len(self._ids):
                return
        self._flush()

    def _flush(self) -> None:
        try:
            if self._binary:
                results: List[Any] = []
                errors: Dict[str, str] = {}
                for i, qid in enumerate(self._ids):
                    kind, value = self._out[qid]
                    if kind == "error":
                        errors[str(i)] = value
                        results.append(None)
                    else:
                        results.append(value)
                msg: Dict[str, Any] = {"ids": self._ids, "results": results}
                if errors:
                    msg["errors"] = errors
                trace_out = None
                if self.trace_meta is not None:
                    with self._lock:
                        trace_out = {"id": self.trace_meta.get("id"),
                                     "spans": list(self._spans)}
                self._rq.push(wire.encode(msg, trace=trace_out))
            else:
                # legacy listener compatibility: per-id JSON messages
                for qid in self._ids:
                    kind, value = self._out[qid]
                    payload = ({"id": qid, "error": value}
                               if kind == "error"
                               else {"id": qid, "result": value})
                    self._rq.push(json.dumps(
                        payload, default=json_default).encode())
        except Exception:
            logger.exception("dropping response frame for %d queries",
                             len(self._ids))


class ShmWorkerQueue:
    """Worker-side view: drains query batches, pushes responses.

    Duck-types cache.queue.WorkerQueue's `take_batch` but yields
    (ResponseHandle, query) pairs — the handle writes into its frame's
    shared :class:`_FrameResponder` instead of resolving an in-process
    future.
    """

    #: batches from this queue serialize at resolve time (the responder
    #: encodes inside the worker's resolve loop, before the next take),
    #: so the worker may assemble them into a REUSED batch buffer
    #: (worker/inference.py) without aliasing hazards
    reusable_batch_ok = True

    class ResponseHandle:
        __slots__ = ("_responder", "_id")

        def __init__(self, responder: _FrameResponder, qid: str):
            self._responder = responder
            self._id = qid

        @property
        def trace(self):
            """Span sink for the worker loop (duck-typed with
            QueryFuture.trace): the frame's responder when this query's
            request is sampled, else None."""
            r = self._responder
            return r if r.trace_meta is not None else None

        def set_result(self, value: Any) -> None:
            self._responder.resolve(self._id, "result", value)

        def set_error(self, error: BaseException) -> None:
            self._responder.resolve(self._id, "error", str(error))

    def __init__(self, query_q: ShmMessageQueue, response_q: ShmMessageQueue):
        self._qq = query_q
        self._rq = response_q
        self._wire_errors = 0  # undecodable frames dropped (see stats())
        from rafiki_tpu.utils.metrics import REGISTRY

        self._m_wire_errors = REGISTRY.counter(
            "rafiki_wire_errors_total",
            "undecodable wire frames dropped (query + response sides)")
        self._m_expired = REGISTRY.counter(
            "rafiki_queue_expired_total",
            "queries dropped past their deadline in a worker queue")

    @classmethod
    def attach(cls, prefix: str, inference_job_id: str,
               worker_id: str) -> "ShmWorkerQueue":
        """Open the queues from another process by deterministic name."""
        qq = ShmMessageQueue(
            _qname(prefix, "q", inference_job_id, worker_id), create=False)
        rq = ShmMessageQueue(
            _qname(prefix, "r", inference_job_id), create=False)
        return cls(qq, rq)

    def stats(self) -> Dict[str, int]:
        """Wire + ring picture folded into SERVING_STATS: undecodable
        frames seen, and the ring occupancy high-water mark as seen from
        THIS handle's pushes (RAFIKI_SHM_RING_BYTES headroom). A worker
        process only pushes the RESPONSE ring, so that is the mark it
        can honestly report; the query ring's mark lives owner-side
        (_SubmitProxy.stats, surfaced via the predictor /healthz)."""
        qr, rr = self._qq.stats(), self._rq.stats()
        return {
            "wire_errors": self._wire_errors,
            "ring_used_bytes": qr["used_bytes"],
            "ring_used_bytes_hw": max(qr["used_bytes_hw"],
                                      rr["used_bytes_hw"]),
        }

    def _pop_decoded(self, timeout_s: float) -> Optional[Tuple[
            List[Tuple[str, Any, Optional[float]]], bool,
            Optional[Dict[str, Any]], float, float]]:
        """Pop + decode one query frame, absorbing corruption: a frame
        that fails to decode is counted and reported as an EMPTY frame
        (([], ...)) — the submitter's SLO timeout covers its queries; the
        worker loop must keep serving. None means ring timeout. The last
        two elements are the monotonic instant decoding started and its
        duration — the codec_decode span of a sampled frame, at its REAL
        interval (queue_wait ends where it begins)."""
        raw = self._qq.pop(timeout_s=timeout_s)
        if raw is None:
            return None
        rule = chaos.hit(chaos.SITE_WIRE, self._qq.name)
        if rule is not None and rule.action == chaos.ACTION_CORRUPT:
            raw = chaos.corrupt_bytes(raw, rule)
        t_pop = time.monotonic()
        try:
            entries, binary, trace_meta = _decode_query_frame(raw)
            return (entries, binary, trace_meta, t_pop,
                    time.monotonic() - t_pop)
        except wire.WireFormatError as e:
            self._wire_errors += 1
            self._m_wire_errors.inc()
            logger.error("dropping undecodable query frame on %s: %s",
                         self._qq.name, e)
            return [], False, None, t_pop, 0.0

    def take_batch(self, max_size: int, deadline_s: float,
                   wait_timeout_s: float = 0.5
                   ) -> Optional[List[Tuple["ShmWorkerQueue.ResponseHandle",
                                            Any]]]:
        """[] on timeout; None once the queue is closed-and-drained (same
        contract as cache.queue.WorkerQueue.take_batch — a closed ring
        answers instantly, and callers polling it as if it were a timeout
        would spin hot). One popped frame carries a whole request's
        queries; draining stops once ``max_size`` is reached (a single
        frame larger than ``max_size`` is still served whole — requests
        are admitted as units)."""
        try:
            first = self._pop_decoded(timeout_s=wait_timeout_s)
        except ShmQueueClosed:
            return None
        if first is None:
            return []
        groups = [first]
        n_entries = len(first[0])
        t0 = time.monotonic()
        while n_entries < max_size:
            # drain whatever is ALREADY in the ring without waiting — same
            # contract as WorkerQueue.take_batch (the deadline is only an
            # optional coalescing wait, and at the default 0 the already-
            # queued frames must still come out as one batch)
            try:
                nxt = self._pop_decoded(timeout_s=0)
                if nxt is None:
                    remaining = deadline_s - (time.monotonic() - t0)
                    if remaining <= 0:
                        break
                    nxt = self._pop_decoded(timeout_s=remaining)
            except ShmQueueClosed:
                break
            if nxt is None:
                break
            groups.append(nxt)
            n_entries += len(nxt[0])
        out: List[Tuple[ShmWorkerQueue.ResponseHandle, Any]] = []
        now = time.monotonic()
        for entries, binary, trace_meta, t_pop, decode_s in groups:
            if not entries:
                continue  # corrupt frame already absorbed
            responder = _FrameResponder(
                self._rq, [qid for qid, _, _ in entries], binary,
                trace_meta=trace_meta)
            anchor = responder.anchor
            if anchor is not None:
                # worker-side half of the sampled request's span tree:
                # queue_wait (submit ts -> this frame's pop, both on the
                # host's shared CLOCK_MONOTONIC) then the decode at its
                # actual interval — the phases tile, they don't overlap
                responder.add_span("queue_wait", anchor, t_pop)
                responder.add_span("codec_decode", t_pop, t_pop + decode_s)
            for qid, query, deadline in entries:
                handle = self.ResponseHandle(responder, qid)
                # overload control: a query whose request deadline passed
                # while it sat in the ring is dropped here, not served —
                # CLOCK_MONOTONIC is system-wide on one host, so the
                # submitter's absolute deadline is directly comparable in
                # this worker process
                if deadline is not None and now >= deadline:
                    self._m_expired.inc()
                    handle.set_error(TimeoutError(
                        "query expired in the shm queue before dispatch"))
                    continue
                out.append((handle, query))
        return out

    def close(self) -> None:
        self._qq.close()


class _SubmitProxy:
    """Predictor-side view of one worker's query queue.

    Overload control happens owner-side (this process): the broker counts
    each worker's *outstanding* queries (submitted, not yet answered), so
    ``depth()`` gives the hedge-suppression/admission load signal and
    ``submit_many`` enforces RAFIKI_PREDICT_QUEUE_DEPTH with the same
    QueueFullError contract as the in-process queue — the shm ring itself
    cannot be asked its message count from here."""

    def __init__(self, broker: "ShmBroker", job_id: str, worker_id: str,
                 query_q: ShmMessageQueue):
        self._broker = broker
        self._job_id = job_id
        self._worker_id = worker_id
        self._qq = query_q

    def depth(self) -> int:
        return self._broker._outstanding_count(self._job_id, self._worker_id)

    def stats(self) -> Dict[str, int]:
        """Submit-side queue picture: outstanding depth plus the query
        ring's occupancy high-water mark (is RAFIKI_SHM_RING_BYTES sized
        for the batched frames actually flowing?)."""
        ring = self._qq.stats()
        return {
            "depth": self.depth(),
            "ring_capacity": ring["capacity"],
            "ring_used_bytes": ring["used_bytes"],
            "ring_used_bytes_hw": ring["used_bytes_hw"],
        }

    def submit(self, query: Any,
               deadline: Optional[float] = None) -> QueryFuture:
        return self.submit_many([query], deadline=deadline)[0]

    def submit_many(self, queries: List[Any],
                    deadline: Optional[float] = None,
                    trace=None) -> List[QueryFuture]:
        """One wire frame per request (cache/wire.py): the whole request
        travels as a single binary message and lands as one worker batch
        by construction. The depth-cap check is all-or-nothing per
        request, like WorkerQueue.submit_many, and the reservation is
        atomic with it (released on response, push failure, or expiry).

        Push failures keep the shed contract typed: a full ring maps to
        the retryable :class:`QueueFullError`, an oversized frame to the
        permanent :class:`FrameTooLargeError` (413 at the doors — split
        the request or raise RAFIKI_SHM_RING_BYTES).

        A sampled request's ``trace`` context crosses the ring in the
        frame metadata; the worker's spans come home in the response
        frame and the broker listener grafts them onto ``trace``."""
        self._broker._reserve_capacity(
            self._job_id, self._worker_id, len(queries))
        ids = [uuid.uuid4().hex for _ in queries]
        futs = [QueryFuture() for _ in queries]
        trace_meta = None
        if trace is not None:
            trace.mark_submitted()
            trace_meta = {**trace.ctx.to_wire(), "ts": trace.t_submit}
        for qid, fut in zip(ids, futs):
            # absolute monotonic deadline; comparable worker-side because
            # both processes share the host's CLOCK_MONOTONIC
            self._broker._register_pending(
                self._job_id, self._worker_id, qid, fut, deadline,
                trace=trace)
        try:
            self._qq.push(_encode_query_frame(ids, queries, deadline,
                                              trace_meta=trace_meta))
        except BaseException as e:
            for qid in ids:
                self._broker._pop_pending(self._job_id, qid)
            if isinstance(e, TimeoutError):
                # ring full past the push timeout: transient backpressure,
                # same retryable shed signal as a full bounded queue
                raise QueueFullError(
                    f"shm ring to worker {self._worker_id} full "
                    f"(ring {self._qq.stats()['used_bytes']}B used)") from e
            if isinstance(e, ValueError):
                raise FrameTooLargeError(
                    f"request frame for {len(queries)} queries exceeds the "
                    f"shm ring capacity (RAFIKI_SHM_RING_BYTES) — split "
                    f"the request or raise the ring size: {e}") from e
            for fut in futs:
                fut.set_error(e)
        return futs


class ShmBroker(Broker):
    """Owner (predictor-process) side of the shm data plane."""

    def __init__(self, prefix: Optional[str] = None,
                 queue_capacity: Optional[int] = None):
        if not available():
            raise RuntimeError("native shmqueue unavailable")
        self.prefix = prefix or f"rafiki{uuid.uuid4().hex[:8]}"
        self._capacity = queue_capacity  # None -> RAFIKI_SHM_RING_BYTES
        self._lock = threading.Lock()
        self._query_qs: Dict[str, Dict[str, ShmMessageQueue]] = {}
        self._response_qs: Dict[str, ShmMessageQueue] = {}
        # qid -> (future, worker_id, expiry_ts): worker_id feeds the
        # per-worker outstanding counts (the depth signal), expiry_ts lets
        # a never-answered query (worker crashed mid-batch) be pruned
        # instead of counting against the depth cap forever
        self._pending: Dict[str, Dict[str, Tuple[QueryFuture, str, float]]] = {}
        self._outstanding: Dict[Tuple[str, str], int] = {}
        self._listeners: Dict[str, threading.Thread] = {}
        self._graveyard: List[ShmMessageQueue] = []
        self.wire_errors = 0  # undecodable response frames dropped
        self._closed = False
        # registry mirrors of the owner-side shed/expiry counters — the
        # shm twin of WorkerQueue's (utils/metrics.py)
        from rafiki_tpu.utils.metrics import REGISTRY

        self._m_rejected = REGISTRY.counter(
            "rafiki_queue_rejected_total",
            "queries refused by a bounded worker queue's depth cap")
        self._m_expired = REGISTRY.counter(
            "rafiki_queue_expired_total",
            "queries dropped past their deadline in a worker queue")

    # -- Broker interface --------------------------------------------------

    def register_worker(self, inference_job_id: str,
                        worker_id: str) -> ShmWorkerQueue:
        with self._lock:
            rq = self._ensure_response_queue(inference_job_id)
            qq = ShmMessageQueue(
                _qname(self.prefix, "q", inference_job_id, worker_id),
                capacity=self._capacity, create=True)
            self._query_qs.setdefault(inference_job_id, {})[worker_id] = qq
        # a same-process worker thread shares the owner's handles; a separate
        # worker process uses ShmWorkerQueue.attach() instead
        return ShmWorkerQueue(qq, rq)

    def unregister_worker(self, inference_job_id: str, worker_id: str) -> None:
        with self._lock:
            qq = self._query_qs.get(inference_job_id, {}).pop(worker_id, None)
            if qq is not None:
                # close only — a _SubmitProxy snapshot taken before this call
                # may still hold the handle, and destroy() munmaps under it
                # (closed pushes fail cleanly; unmapped ones segfault).
                # The segment is reclaimed at broker close().
                qq.close()
                self._graveyard.append(qq)

    def get_worker_queues(self, inference_job_id: str) -> Dict[str, Any]:
        with self._lock:
            return {
                wid: _SubmitProxy(self, inference_job_id, wid, qq)
                for wid, qq in self._query_qs.get(inference_job_id, {}).items()
            }

    # -- response plumbing -------------------------------------------------

    def _ensure_response_queue(  # guarded-by: _lock
            self, job_id: str) -> ShmMessageQueue:
        """Caller holds self._lock."""
        if job_id not in self._response_qs:
            rq = ShmMessageQueue(
                _qname(self.prefix, "r", job_id),
                capacity=self._capacity, create=True)
            self._response_qs[job_id] = rq
            self._pending[job_id] = {}
            t = threading.Thread(
                target=self._listen, args=(job_id, rq),
                name=f"shm-listener-{job_id[:8]}", daemon=True)
            self._listeners[job_id] = t
            t.start()
        return self._response_qs[job_id]

    def _register_pending(self, job_id: str, worker_id: str, qid: str,
                          fut: QueryFuture,
                          deadline: Optional[float], trace=None) -> None:
        """Record one reserved query's future (the outstanding count was
        already taken by _reserve_capacity — registering must NOT count
        again). Expiry gets a grace period past the request deadline (or
        the configured SLO): a query the worker never answers must stop
        counting against its depth eventually, or one crash would pin the
        replica "full" forever."""
        from rafiki_tpu import config

        expiry = (deadline if deadline is not None
                  else time.monotonic() + config.PREDICT_TIMEOUT_S) + 30.0
        with self._lock:
            self._pending.setdefault(job_id, {})[qid] = (
                fut, worker_id, expiry, trace)

    def _pop_pending(self, job_id: str, qid: str) -> Optional[QueryFuture]:
        fut, _ = self._pop_pending_traced(job_id, qid)
        return fut

    def _pop_pending_traced(self, job_id: str, qid: str):
        """(future, trace) for one pending id — (None, None) if unknown."""
        with self._lock:
            entry = self._pending.get(job_id, {}).pop(qid, None)
            if entry is None:
                return None, None
            fut, worker_id, _, trace = entry
            self._dec_outstanding_locked(job_id, worker_id)
            return fut, trace

    def _dec_outstanding_locked(self, job_id: str,  # guarded-by: _lock
                                worker_id: str) -> None:
        key = (job_id, worker_id)
        n = self._outstanding.get(key, 0) - 1
        if n <= 0:
            self._outstanding.pop(key, None)
        else:
            self._outstanding[key] = n

    def _prune_expired_locked(self, job_id: str, worker_id: str) -> None:
        """Drop never-answered entries past their expiry (worker crashed
        mid-batch). Must run on EVERY read of the count, not just on
        submits: the admission layer sheds on depth() *before* any submit
        happens, so a prune that only ran at submit time could never fire
        again once phantoms pushed the estimated wait over every
        deadline — a permanent-429 lockout."""
        now = time.monotonic()
        job_pending = self._pending.get(job_id, {})
        for qid, (_, wid, expiry, _trace) in list(job_pending.items()):
            if wid == worker_id and now >= expiry:
                job_pending.pop(qid)
                self._dec_outstanding_locked(job_id, wid)
                self._m_expired.inc()

    def _outstanding_count(self, job_id: str, worker_id: str) -> int:
        with self._lock:
            if self._outstanding.get((job_id, worker_id), 0) > 0:
                self._prune_expired_locked(job_id, worker_id)
            return self._outstanding.get((job_id, worker_id), 0)

    def _reserve_capacity(self, job_id: str, worker_id: str, n: int) -> None:
        """Atomically check RAFIKI_PREDICT_QUEUE_DEPTH and claim ``n``
        outstanding slots (one lock hold: a check-then-register split
        would let concurrent submitters jointly overshoot the cap). The
        claim is released by _pop_pending (response/push-failure) or by
        expiry pruning."""
        from rafiki_tpu import config

        cap = int(config.PREDICT_QUEUE_DEPTH)
        key = (job_id, worker_id)
        with self._lock:
            if self._outstanding.get(key, 0) > 0:
                self._prune_expired_locked(job_id, worker_id)
            queued = self._outstanding.get(key, 0)
            if cap > 0 and queued + n > cap:
                self._m_rejected.inc(n)
                raise QueueFullError(
                    f"shm worker {worker_id} full "
                    f"({queued}/{cap} outstanding)")
            self._outstanding[key] = queued + n

    def _resolve_response(self, job_id: str, msg: Any,
                          meta: Optional[Dict[str, Any]] = None) -> None:
        """Resolve futures for one decoded response message — batched
        frame ({"ids", "results", "errors"}) or legacy per-id JSON.
        ``meta`` may carry the worker's trace spans for a sampled
        request; they are grafted onto the request's RequestTrace before
        its futures resolve (the door reads the tree after gather)."""
        if not isinstance(msg, dict):
            raise wire.WireFormatError("response frame is not an object")
        trace_meta = (meta or {}).get("trace")
        wire_spans = (trace_meta.get("spans")
                      if isinstance(trace_meta, dict) else None)
        if "id" in msg:  # legacy single-response message
            if not isinstance(msg["id"], str):
                raise wire.WireFormatError("response id is not a string")
            fut = self._pop_pending(job_id, msg["id"])
            if fut is None:
                return
            if "error" in msg:
                fut.set_error(RuntimeError(msg["error"]))
            else:
                fut.set_result(msg.get("result"))
            return
        # validate EVERY field before touching pending state: a frame
        # that decodes but is malformed (results not a sequence,
        # non-string ids, errors not a dict) must raise the one typed
        # error _listen absorbs — the listener thread outlives any bad
        # message, or the whole job's futures strand forever
        try:
            ids = msg["ids"]
            results = msg["results"]
            errors = msg.get("errors") or {}
            if (not isinstance(ids, list)
                    or not all(isinstance(i, str) for i in ids)
                    or not isinstance(results, list)
                    or not isinstance(errors, dict)
                    or len(results) != len(ids)):
                raise wire.WireFormatError("malformed response frame")
        except (KeyError, TypeError) as e:
            raise wire.WireFormatError(
                f"malformed response frame: {e}") from e
        for i, qid in enumerate(ids):
            fut, trace = self._pop_pending_traced(job_id, qid)
            if fut is None:
                continue
            if wire_spans is not None and trace is not None:
                # one graft per response frame (a request's futures share
                # the trace; spans are offsets against ITS submit time)
                trace.add_wire_spans(wire_spans, anchor=trace.t_submit)
                wire_spans = None
            err = errors.get(str(i))
            if err is not None:
                fut.set_error(RuntimeError(err))
            else:
                fut.set_result(results[i])

    def _listen(self, job_id: str, rq: ShmMessageQueue) -> None:
        while not self._closed:
            try:
                raw = rq.pop(timeout_s=0.5)
            except ShmQueueClosed:
                break
            except Exception:
                logger.exception("response listener %s died", job_id)
                break
            if raw is None:
                continue
            rule = chaos.hit(chaos.SITE_WIRE, rq.name)
            if rule is not None and rule.action == chaos.ACTION_CORRUPT:
                raw = chaos.corrupt_bytes(raw, rule)
            try:
                body, meta = wire.decode_any_meta(raw)
                self._resolve_response(job_id, body, meta)
            except wire.WireFormatError as e:
                # a corrupt response frame is absorbed here: its pending
                # futures keep waiting and resolve with the request's own
                # (typed) TimeoutError at the SLO — the listener thread
                # must outlive any single bad message
                self._count_wire_error()
                logger.error("dropping undecodable response frame on %s: %s",
                             job_id, e)
                continue

    def _count_wire_error(self) -> None:
        """One undecodable frame. Under the lock: each job's listener is
        its own thread, and sibling listeners doing a bare ``+=`` on the
        shared counter lose updates against each other (found by the
        concurrency lint, CONC302)."""
        with self._lock:
            self.wire_errors += 1
        from rafiki_tpu.utils.metrics import REGISTRY

        REGISTRY.counter(
            "rafiki_wire_errors_total",
            "undecodable wire frames dropped (query + response "
            "sides)").inc()

    # -- lifecycle ---------------------------------------------------------

    def close(self) -> None:
        self._closed = True
        with self._lock:
            jobs = list(self._query_qs)
            for job_id in jobs:
                for qq in self._query_qs[job_id].values():
                    qq.close()
                    qq.destroy()
            self._query_qs.clear()
            for qq in self._graveyard:
                qq.destroy()
            self._graveyard.clear()
            for rq in self._response_qs.values():
                rq.close()
        for t in self._listeners.values():
            t.join(timeout=2.0)
        with self._lock:
            for rq in self._response_qs.values():
                rq.destroy()
            self._response_qs.clear()
            for pend in self._pending.values():
                for fut, _, _, _ in pend.values():
                    fut.set_error(RuntimeError("broker closed"))
            self._pending.clear()
            self._outstanding.clear()


class ShmBrokerClient:
    """Worker-process side of the shm data plane.

    The owner (`ShmBroker`, in the admin/predictor process) creates the
    segments when a serving service is placed; a worker process built by
    ProcessPlacementManager attaches to them by deterministic name —
    the analogue of the reference's workers connecting to the Redis address
    passed in their container env (reference rafiki/cache/cache.py:21,
    services_manager env plumbing). `register_worker` therefore *attaches*
    (with retry, the owner may still be creating) and `unregister_worker`
    detaches without closing: segment lifecycle belongs to the owner, so a
    crashed-and-restarted worker can re-attach and resume serving.
    """

    def __init__(self, prefix: str, attach_timeout_s: float = 10.0):
        self.prefix = prefix
        self._attach_timeout_s = attach_timeout_s
        self._queues: Dict[Tuple[str, str], ShmWorkerQueue] = {}

    def register_worker(self, inference_job_id: str,
                        worker_id: str) -> ShmWorkerQueue:
        deadline = time.monotonic() + self._attach_timeout_s
        while True:
            try:
                wq = ShmWorkerQueue.attach(
                    self.prefix, inference_job_id, worker_id)
                break
            except Exception:
                if time.monotonic() >= deadline:
                    raise
                time.sleep(0.05)
        self._queues[(inference_job_id, worker_id)] = wq
        return wq

    def unregister_worker(self, inference_job_id: str, worker_id: str) -> None:
        wq = self._queues.pop((inference_job_id, worker_id), None)
        if wq is not None:
            # detach only (munmap, no shm_unlink — we are not the owner);
            # do NOT close: the shared closed flag would kill the segment
            # for the owner and for any restarted worker
            wq._qq.destroy()
            wq._rq.destroy()

    def get_worker_queues(self, inference_job_id: str) -> Dict[str, Any]:
        raise NotImplementedError(
            "worker-side broker client cannot enumerate queues; the "
            "predictor runs in the owner process")


def make_broker() -> Broker:
    """RAFIKI_BROKER=shm -> native cross-process broker (with fallback);
    anything else -> in-process condition-variable broker."""
    import os

    from rafiki_tpu.cache.queue import InProcessBroker

    if os.environ.get("RAFIKI_BROKER") == "shm":
        try:
            return ShmBroker()
        except Exception:
            logger.warning("shm broker unavailable; using in-process broker")
    return InProcessBroker()
