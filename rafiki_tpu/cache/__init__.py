"""Serving data plane: query/prediction transport between predictor and
inference workers (reference rafiki/cache/ — Redis lists/sets)."""

from rafiki_tpu.cache.queue import InProcessBroker, QueryFuture, WorkerQueue  # noqa: F401
