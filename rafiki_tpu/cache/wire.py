"""Versioned binary wire codec for the serving data plane.

Every internal hop of the serving path (shm broker frames, the fleet
HTTP relay) used to ride ``utils/jsonutil.py``, which turns each ndarray
into float *text* (``tolist()``) — ~20 bytes and a float parse per
element, which for a dense 3072-float query is the transport's CPU, not
the model (BENCH_r05: the JSON door saturates at ~1/3 the binary door's
throughput on the same model). This module is the binary replacement:
ndarrays travel as raw C-contiguous bytes behind a tiny JSON header and
decode with **zero-copy** ``np.frombuffer`` views into the frame.

Frame layout (all integers little-endian)::

    [0:4]    magic  b"\\xabRWF"   (0xAB cannot start UTF-8 JSON text,
                                   so frames and JSON bodies are
                                   sniffable on one byte)
    [4]      version (currently 1)
    [5]      reserved (0)
    [6:10]   u32 header length H
    [10:10+H] header JSON: {"b": <body>, "a": [[dtype, shape, off, nbytes], ...]}
    ...      zero padding to a 16-byte boundary
    [P:]     array payload region; each array 16-byte aligned, ``off``
             relative to P

The body is an arbitrary JSON-able structure in which each ndarray was
replaced by the placeholder ``{"\\u0000nd": k}`` (index into the array
table). Dtypes are stored as ``np.dtype.str`` — byte order included —
so a big-endian array round-trips bit-exact and the decoder never
guesses endianness. Dict keys colliding with the placeholder sentinel
are escaped, so untrusted JSON queries cannot forge an array reference.

Escape hatch: values that are not numeric/bool ndarrays (strings, dicts,
object arrays…) stay inside the JSON header via the shared
``jsonutil.json_default`` convention — a frame with zero arrays is legal,
so non-array traffic rides the same framing. ``decode_any`` sniffs the
magic and falls back to plain ``json.loads``, which is what lets
old-JSON and new-binary peers interoperate on the same queue: receivers
always sniff, senders choose a format (``RAFIKI_WIRE_BINARY=0`` forces
JSON framing everywhere for a version-mismatched fleet).

All malformed input — short frames, bad version, garbled headers,
out-of-range array extents — raises :class:`WireFormatError`, never an
uncaught slice/KeyError: pop loops catch ONE exception type and a
corrupt frame can never crash a worker loop.
"""

from __future__ import annotations

import json
from typing import Any, List

import numpy as np

from rafiki_tpu.utils.jsonutil import json_default

MAGIC = b"\xabRWF"
# v1: header {"b": body, "a": array table}. v2 adds an OPTIONAL "t" key —
# request-trace metadata (utils/trace.py) riding the frame so a sampled
# predict's context crosses the shm hop without touching the body.
# Interop contract: encoders emit v1 whenever no trace metadata is
# attached (bit-identical to the old framing, so old receivers keep
# decoding) and v2 only for sampled requests; decoders accept both.
# Fleet-relay peers advertise SUPPORTED_VERSIONS on /healthz and the
# sender picks the intersection (cache/fleet.py).
VERSION = 2
# v3: the INCREMENTAL-RESPONSE message kind (generative serving,
# docs/serving-generation.md). A v3 frame is an ordinary frame whose
# header carries a "g" key — {"sid": sequence id, "fin": finished flag,
# "reason": finish reason, "err": terminal error} — and whose single
# array-table entry is the delta's token ids. Token-delta frames are
# version-marked 3 precisely so an OLD peer can never half-understand
# one: a {1,2} decoder answers the typed WireFormatError("unsupported
# wire version"), and senders consult the peer's advertised versions
# (the /healthz wire_versions handshake; the streaming door's explicit
# Accept opt-in) before ever emitting one. Non-generative traffic keeps
# emitting v1/v2 byte-identically.
TOKEN_DELTA_VERSION = 3
SUPPORTED_VERSIONS = frozenset({1, 2, 3})
_ALIGN = 16
# HTTP Content-Type for frames on the fleet relay (placement/agent.py
# negotiates it via the /healthz "wire_versions" advertisement)
CONTENT_TYPE = "application/x-rafiki-wire"

# placeholder/escape sentinels: NUL ("\\x00") cannot appear in sane user keys,
# but nothing stops a hostile JSON query from sending it — hence _ESC
_ND_KEY = "\x00nd"
_ESC_KEY = "\x00esc"

# dtype kinds that travel as raw bytes (bool, (u)int, float, complex);
# everything else falls back to the JSON escape hatch
_BINARY_KINDS = frozenset("biufc")


class WireFormatError(ValueError):
    """Frame failed to parse (truncated, garbled, unknown version)."""


def binary_enabled() -> bool:
    """Global sender-side switch: RAFIKI_WIRE_BINARY=0 forces JSON
    framing (receivers always sniff both, so this is the operator's
    escape hatch for a mixed-version fleet)."""
    import os

    return os.environ.get("RAFIKI_WIRE_BINARY", "1") not in ("0", "false")


def _pad16(n: int) -> int:
    return (-n) % _ALIGN


def _strip_arrays(obj: Any, arrays: List[np.ndarray]) -> Any:
    """Replace every binary-kind ndarray in ``obj`` with a placeholder,
    collecting the (C-contiguous) arrays; escape colliding dict keys."""
    if isinstance(obj, np.ndarray):
        if obj.dtype.kind in _BINARY_KINDS:
            a = np.ascontiguousarray(obj)
            if a.shape != obj.shape:  # ascontiguousarray promotes 0-d to 1-d
                a = a.reshape(obj.shape)
            arrays.append(a)
            return {_ND_KEY: len(arrays) - 1}
        return obj.tolist()  # str/object arrays: JSON escape hatch
    if isinstance(obj, np.generic):
        if obj.dtype.kind in _BINARY_KINDS:
            arrays.append(np.asarray(obj))  # 0-d array
            return {_ND_KEY: len(arrays) - 1}
        return obj.item()
    if isinstance(obj, dict):
        out = {k: _strip_arrays(v, arrays) for k, v in obj.items()}
        if _ND_KEY in obj or _ESC_KEY in obj:
            # a user dict that *looks like* a placeholder must never
            # decode as one (type confusion on untrusted queries)
            return {_ESC_KEY: out}
        return out
    if isinstance(obj, (list, tuple)):
        return [_strip_arrays(v, arrays) for v in obj]
    return obj


def _restore_arrays(obj: Any, views: List[np.ndarray]) -> Any:
    if isinstance(obj, dict):
        if _ND_KEY in obj:
            try:
                return views[int(obj[_ND_KEY])]
            except (IndexError, TypeError, ValueError) as e:
                raise WireFormatError(f"bad array reference: {e}") from e
        if _ESC_KEY in obj:
            inner = obj[_ESC_KEY]
            if not isinstance(inner, dict):
                raise WireFormatError("bad escape wrapper")
            return {k: _restore_arrays(v, views) for k, v in inner.items()}
        return {k: _restore_arrays(v, views) for k, v in obj.items()}
    if isinstance(obj, list):
        return [_restore_arrays(v, views) for v in obj]
    return obj


def encode(obj: Any, trace: Any = None) -> bytes:
    """One binary frame for ``obj`` (any JSON-able structure, ndarrays
    at any depth). Raises TypeError for non-JSON, non-array leaves —
    same contract as the JSON wire convention it replaces.

    ``trace`` (a JSON-able dict, utils/trace.py wire shape) rides the v2
    frame header's "t" key; without it the frame is emitted as v1, byte
    identical to the pre-trace codec, so unsampled traffic stays
    decodable by old peers."""
    arrays: List[np.ndarray] = []
    body = _strip_arrays(obj, arrays)
    table = []
    off = 0
    for a in arrays:
        off += _pad16(off)
        table.append([a.dtype.str, list(a.shape), off, a.nbytes])
        off += a.nbytes
    hdr: dict = {"b": body, "a": table}
    version = 1
    if trace is not None:
        hdr["t"] = trace
        version = VERSION
    header = json.dumps(hdr, default=json_default).encode()
    pieces = [MAGIC, bytes([version, 0]),
              len(header).to_bytes(4, "little"), header,
              b"\x00" * _pad16(len(MAGIC) + 2 + 4 + len(header))]
    pos = 0
    for a, (_, _, o, _) in zip(arrays, table):
        if o > pos:
            pieces.append(b"\x00" * (o - pos))
            pos = o
        pieces.append(a.tobytes())  # C-contiguous by construction
        pos += a.nbytes
    return b"".join(pieces)


def is_frame(raw: bytes) -> bool:
    return len(raw) >= 4 and raw[:4] == MAGIC


def decode(raw: bytes) -> Any:
    """Decode one frame. Array leaves come back as **read-only
    zero-copy views** into ``raw`` (they keep the frame alive); callers
    that mutate must copy."""
    return decode_meta(raw)[0]


def decode_meta(raw: bytes, versions: frozenset = SUPPORTED_VERSIONS
                ) -> tuple:
    """Like :func:`decode` but returns ``(body, meta)`` where ``meta`` is
    the frame-level metadata dict — ``{"trace": ...}`` for a v2 frame
    carrying request-trace context, ``{"gen": ...}`` for a v3 token-delta
    frame, ``{}`` otherwise. ``versions`` narrows what this receiver
    accepts (tests model old peers with it; the default is everything
    this build speaks)."""
    if not is_frame(raw):
        raise WireFormatError("not a wire frame (bad magic)")
    if len(raw) < 10:
        raise WireFormatError("truncated frame header")
    if raw[4] not in versions:
        raise WireFormatError(f"unsupported wire version {raw[4]}")
    hlen = int.from_bytes(raw[6:10], "little")
    if 10 + hlen > len(raw):
        raise WireFormatError("truncated frame (header extent)")
    try:
        header = json.loads(raw[10:10 + hlen])
        body, table = header["b"], header["a"]
    except (ValueError, KeyError, TypeError, UnicodeDecodeError) as e:
        raise WireFormatError(f"garbled frame header: {e}") from e
    meta = {}
    if isinstance(header, dict) and "t" in header:
        meta["trace"] = header["t"]
    if isinstance(header, dict) and "g" in header:
        meta["gen"] = header["g"]
    payload_start = 10 + hlen + _pad16(10 + hlen)
    payload = memoryview(raw)[payload_start:]
    views: List[np.ndarray] = []
    if not isinstance(table, list):
        raise WireFormatError("garbled array table")
    for entry in table:
        try:
            dtype_str, shape, off, nbytes = entry
            dt = np.dtype(dtype_str)
            shape = tuple(int(s) for s in shape)
            off, nbytes = int(off), int(nbytes)
        except (ValueError, TypeError) as e:
            raise WireFormatError(f"garbled array entry: {e}") from e
        if dt.kind not in _BINARY_KINDS:
            raise WireFormatError(f"non-binary dtype {dtype_str!r} on wire")
        if any(s < 0 for s in shape):
            raise WireFormatError("negative array dimension")
        # Python-int product: a hostile shape like [2**32, 2**32] must
        # not wrap to 0 the way a fixed-width product would and slip
        # past the extent check
        expected = dt.itemsize
        for s in shape:
            expected *= s
        if nbytes != expected or off < 0 or off + nbytes > len(payload):
            raise WireFormatError("array extent out of range")
        try:
            views.append(np.frombuffer(
                payload[off:off + nbytes], dtype=dt).reshape(shape))
        except ValueError as e:  # belt-and-braces: numpy's own refusals
            raise WireFormatError(f"bad array extent: {e}") from e
    return _restore_arrays(body, views), meta


def decode_any(raw: bytes) -> Any:
    """The receiver-side sniff: binary frame -> :func:`decode`; anything
    else is parsed as JSON (the legacy framing). This single entry point
    is what makes every receive end mixed-version tolerant."""
    return decode_any_meta(raw)[0]


def decode_any_meta(raw: bytes) -> tuple:
    """Sniffing twin of :func:`decode_meta`: ``(body, meta)`` for frames,
    ``(json.loads(raw), {})`` for legacy JSON."""
    if is_frame(raw):
        return decode_meta(raw)
    try:
        return json.loads(raw), {}
    except (ValueError, UnicodeDecodeError) as e:
        raise WireFormatError(f"neither wire frame nor JSON: {e}") from e


# -- incremental-response message kind (generative serving) ------------------

def encode_token_delta(seq_id: str, tokens, finished: bool = False,
                       reason: Any = None, error: Any = None) -> bytes:
    """One v3 token-delta frame: sequence id + this increment's token ids
    + the finished flag (and, on the terminal delta, the finish reason /
    typed error text). The streaming door emits these to clients that
    opted in via Accept, and the shm/fleet hops may relay them to peers
    advertising wire version 3 — an old peer rejects the version byte
    with a typed WireFormatError before ever misreading the kind."""
    arr = np.ascontiguousarray(np.asarray(list(tokens), dtype=np.int32))
    g: dict = {"sid": str(seq_id), "fin": bool(finished)}
    if reason is not None:
        g["reason"] = str(reason)
    if error is not None:
        g["err"] = str(error)
    table = [[arr.dtype.str, list(arr.shape), 0, arr.nbytes]]
    header = json.dumps({"b": {_ND_KEY: 0}, "a": table, "g": g}).encode()
    return b"".join([
        MAGIC, bytes([TOKEN_DELTA_VERSION, 0]),
        len(header).to_bytes(4, "little"), header,
        b"\x00" * _pad16(len(MAGIC) + 2 + 4 + len(header)),
        arr.tobytes()])


def is_token_delta(raw: bytes) -> bool:
    """Cheap sniff: a frame whose version byte marks the incremental-
    response kind (full validation happens in :func:`decode_token_delta`)."""
    return is_frame(raw) and len(raw) >= 5 and raw[4] == TOKEN_DELTA_VERSION


def decode_token_delta(raw: bytes,
                       versions: frozenset = SUPPORTED_VERSIONS):
    """Decode one incremental-response frame into ``(seq_id,
    TokenDelta)``. Every malformed shape — missing "g" metadata, wrong
    field types, non-integer token payload, truncation — raises the one
    :class:`WireFormatError` receivers already absorb."""
    from rafiki_tpu.cache.queue import TokenDelta

    body, meta = decode_meta(raw, versions)
    g = meta.get("gen")
    if not isinstance(g, dict):
        raise WireFormatError("frame carries no token-delta metadata")
    sid, fin = g.get("sid"), g.get("fin")
    if not isinstance(sid, str) or not isinstance(fin, bool):
        raise WireFormatError("garbled token-delta metadata (sid/fin)")
    reason, err = g.get("reason"), g.get("err")
    if ((reason is not None and not isinstance(reason, str))
            or (err is not None and not isinstance(err, str))):
        raise WireFormatError("garbled token-delta metadata (reason/err)")
    if not isinstance(body, np.ndarray) or body.dtype.kind not in "iu":
        raise WireFormatError("token-delta payload is not an integer array")
    return sid, TokenDelta([int(t) for t in body.ravel()],
                           finished=fin, reason=reason, error=err)


def dumps(obj: Any, trace: Any = None) -> bytes:
    """Sender-side entry point: binary frame, or the legacy JSON framing
    when RAFIKI_WIRE_BINARY=0 (trace metadata rides only the binary
    frame header — the JSON escape hatch predates it)."""
    if binary_enabled():
        return encode(obj, trace=trace)
    return json.dumps(obj, default=json_default).encode()


# -- content digests (prediction result cache) -------------------------------

def canonical_digest(obj: Any) -> "str | None":
    """Stable content hash of one query for the prediction result cache
    (predictor/result_cache.py): two byte-identical queries must map to
    one digest however they arrived. Array-bearing payloads ride the
    v1 binary encoding (dtype + shape + raw bytes — the same canonical
    form every serving hop already speaks, so a binary-door query and
    its JSON-door twin hash alike once decoded); everything else falls
    back to sorted-key canonical JSON. Returns ``None`` for payloads
    with no canonical encoding (exotic objects) — the cache treats those
    as permanently uncacheable, never an error on the serving path.

    Collision stance: blake2b-128 over the canonical bytes. A cache hit
    substitutes one model forward for another, so the only damage a
    collision could do is serve query A's prediction to query B — at
    2^64 birthday cost that is not a realistic event, and the cache is
    flushed on every model-version change regardless.
    """
    import hashlib

    try:
        if isinstance(obj, np.ndarray) or _has_array(obj):
            raw = encode(obj)
        else:
            raw = json.dumps(obj, sort_keys=True,
                             separators=(",", ":")).encode()
    except (TypeError, ValueError):
        return None
    return hashlib.blake2b(raw, digest_size=16).hexdigest()


def _has_array(obj: Any, depth: int = 0) -> bool:
    """True when ``obj`` carries an ndarray/numpy scalar anywhere a
    frame encoder would find one (bounded depth — a pathological deep
    query just takes the JSON fallback)."""
    if depth > 8:
        return False
    if isinstance(obj, (np.ndarray, np.generic)):
        return True
    if isinstance(obj, dict):
        return any(_has_array(v, depth + 1) for v in obj.values())
    if isinstance(obj, (list, tuple)):
        return any(_has_array(v, depth + 1) for v in obj)
    return False


def stackable(queries: List[Any]) -> bool:
    """True when ``queries`` is a non-empty homogeneous batch of numeric
    ndarrays (same dtype+shape) — the single definition of 'stackable'
    shared by every hop that turns a request's rows into one contiguous
    array (shm framing, fleet relay, worker batch assembly)."""
    first = queries[0] if queries else None
    return (isinstance(first, np.ndarray)
            and first.dtype.kind in _BINARY_KINDS
            and all(isinstance(q, np.ndarray) and q.dtype == first.dtype
                    and q.shape == first.shape for q in queries))


def stack_batch(queries: List[Any]) -> Any:
    """One ``(n, ...)`` array for a stackable batch (zero-copy for the
    single-row case), or None when the batch is not stackable."""
    if not stackable(queries):
        return None
    return queries[0][None] if len(queries) == 1 else np.stack(queries)
