"""Ring attention: exact attention over a sequence sharded across chips.

Long-context first-class support: each chip holds a sequence shard of q/k/v;
k/v shards rotate around the ``seq`` mesh axis via ``lax.ppermute`` (ICI
neighbour hops) while each chip accumulates its q-shard's attention with
online-softmax statistics — so the full (S, S) score matrix never exists on
any chip and sequence length scales linearly with the number of chips. The
communication pattern matches Ring Attention (blockwise transformers); the
compute per hop is the same online-softmax update as the flash kernel
(ops/flash_attention.py) applied to one (S_local, S_local) tile.

Gradients flow through ``lax.scan`` + ``ppermute`` natively, so this is
trainable without a custom VJP.
"""

from __future__ import annotations

import math
from functools import partial
from typing import Optional

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

from rafiki_tpu.parallel.mesh import DATA_AXIS, SEQ_AXIS
from rafiki_tpu.parallel.sharding import axis_size, shard_map

NEG_INF = -1e30


def _ring_local(q: jax.Array, k: jax.Array, v: jax.Array, *, axis_name: str,
                causal: bool, sm_scale: Optional[float]) -> jax.Array:
    """Per-shard body (inside shard_map): q,k,v are (B, H, S_local, Dh)."""
    n = axis_size(axis_name)
    my = jax.lax.axis_index(axis_name)
    s_local = q.shape[2]
    scale = sm_scale if sm_scale is not None else 1.0 / math.sqrt(q.shape[-1])
    qf = q.astype(jnp.float32) * scale
    perm = [(r, (r + 1) % n) for r in range(n)]

    q_pos = my * s_local + jax.lax.broadcasted_iota(
        jnp.int32, (s_local, s_local), 0)

    def step(carry, i):
        o, m, l, k_cur, v_cur = carry
        src = (my - i) % n  # whose kv shard we hold at step i
        s = jnp.einsum("bhqd,bhkd->bhqk", qf, k_cur.astype(jnp.float32),
                       preferred_element_type=jnp.float32)
        if causal:
            k_pos = src * s_local + jax.lax.broadcasted_iota(
                jnp.int32, (s_local, s_local), 1)
            s = jnp.where(q_pos >= k_pos, s, NEG_INF)
        m_new = jnp.maximum(m, jnp.max(s, axis=-1, keepdims=True))
        p = jnp.exp(s - m_new)
        alpha = jnp.exp(m - m_new)
        l_new = alpha * l + jnp.sum(p, axis=-1, keepdims=True)
        o_new = o * alpha + jnp.einsum(
            "bhqk,bhkd->bhqd", p, v_cur.astype(jnp.float32),
            preferred_element_type=jnp.float32)
        k_nxt = jax.lax.ppermute(k_cur, axis_name, perm)
        v_nxt = jax.lax.ppermute(v_cur, axis_name, perm)
        return (o_new, m_new, l_new, k_nxt, v_nxt), None

    b, h, _, dh = q.shape
    o0 = jnp.zeros((b, h, s_local, dh), jnp.float32)
    m0 = jnp.full((b, h, s_local, 1), NEG_INF, jnp.float32)
    l0 = jnp.zeros((b, h, s_local, 1), jnp.float32)
    (o, _, l, _, _), _ = jax.lax.scan(
        step, (o0, m0, l0, k, v), jnp.arange(n))
    return (o / jnp.maximum(l, 1e-30)).astype(q.dtype)


def ring_attention(q: jax.Array, k: jax.Array, v: jax.Array, mesh: Mesh,
                   causal: bool = False, sm_scale: Optional[float] = None,
                   seq_axis: str = SEQ_AXIS,
                   data_axis: str = DATA_AXIS) -> jax.Array:
    """Exact attention over (B, H, S, Dh) with S sharded over ``seq_axis``
    and B over ``data_axis`` of `mesh`. S must divide by the seq axis size."""
    spec = P(data_axis, None, seq_axis, None)
    fn = shard_map(
        partial(_ring_local, axis_name=seq_axis, causal=causal,
                sm_scale=sm_scale),
        mesh=mesh, in_specs=(spec, spec, spec), out_specs=spec,
        check_vma=False,
    )
    return fn(q, k, v)
