"""Parallelism layer: device meshes, sharding rules, and collectives.

This is the TPU-native replacement for the reference's entire distribution
story — per-GPU Docker containers plus a single in-graph NCCL all-reduce
(reference pg_gans.py:1165-1170, rafiki/container/docker_swarm.py). Here,
parallelism is expressed as shardings over a `jax.sharding.Mesh`; XLA inserts
the collectives (psum/all-gather/reduce-scatter) over ICI.
"""

from rafiki_tpu.parallel.mesh import (  # noqa: F401
    MeshSpec,
    get_default_mesh,
    get_device_grant,
    make_mesh,
    set_device_grant,
    visible_devices,
)
