"""Mixture-of-Experts FFN with expert parallelism.

Switch-style top-1 routing with a static capacity: tokens are dispatched to
experts through one-hot einsums (dense dispatch — static shapes, no gathers,
exactly what XLA tiles well), experts are sharded over the ``expert`` mesh
axis, and GSPMD turns the dispatch/combine einsums into the all-to-alls.
Returns the load-balancing auxiliary loss (Switch Transformer eq. 4) so the
trainer can add it to the objective.
"""

from __future__ import annotations

import math
from typing import Any, Dict, Tuple

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from rafiki_tpu.models.core import normal_init

Params = Dict[str, Any]


def moe_init(rng: jax.Array, dim: int, hidden: int, n_experts: int) -> Params:
    kr, k1, k2 = jax.random.split(rng, 3)
    std1 = math.sqrt(2.0 / dim)
    std2 = math.sqrt(2.0 / hidden)
    return {
        "router": normal_init(kr, (dim, n_experts), std=0.02),
        "w1": normal_init(k1, (n_experts, dim, hidden), std=std1),
        "b1": jnp.zeros((n_experts, hidden), jnp.float32),
        "w2": normal_init(k2, (n_experts, hidden, dim), std=std2),
        "b2": jnp.zeros((n_experts, dim), jnp.float32),
    }


def moe_partition_specs() -> Params:
    return {
        "router": P(None, None),
        "w1": P("expert", None, "model"),
        "b1": P("expert", "model"),
        "w2": P("expert", "model", None),
        "b2": P("expert", None),
    }


def moe_apply(params: Params, x: jax.Array, capacity_factor: float = 1.25
              ) -> Tuple[jax.Array, jax.Array]:
    """x: (B, S, D) -> (y, aux_loss). Tokens over capacity are dropped
    (residual connection carries them — standard Switch behavior)."""
    b, s, d = x.shape
    n_tok = b * s
    xt = x.reshape(n_tok, d)
    n_exp = params["router"].shape[-1]
    capacity = int(math.ceil(n_tok / n_exp * capacity_factor))

    logits = jnp.dot(xt.astype(jnp.float32), params["router"])
    gates = jax.nn.softmax(logits, axis=-1)          # (N, E)
    expert = jnp.argmax(gates, axis=-1)              # (N,)
    gate = jnp.take_along_axis(gates, expert[:, None], axis=-1)[:, 0]

    exp_oh = jax.nn.one_hot(expert, n_exp, dtype=jnp.float32)  # (N, E)
    # position of each token within its expert's queue
    pos = jnp.cumsum(exp_oh, axis=0) * exp_oh - 1.0            # (N, E)
    keep = (pos >= 0) & (pos < capacity)
    pos_oh = jax.nn.one_hot(pos.astype(jnp.int32), capacity,
                            dtype=jnp.float32) * keep[..., None]  # (N, E, C)

    dispatch = pos_oh                                 # (N, E, C)
    combine = dispatch * gate[:, None, None]          # (N, E, C)

    xe = jnp.einsum("nec,nd->ecd", dispatch, xt.astype(jnp.float32))
    he = jax.nn.gelu(
        jnp.einsum("ecd,edh->ech", xe, params["w1"]) + params["b1"][:, None, :])
    ye = jnp.einsum("ech,ehd->ecd", he, params["w2"]) + params["b2"][:, None, :]
    y = jnp.einsum("nec,ecd->nd", combine, ye)

    # Switch load-balancing loss: E * sum_e f_e * p_e
    frac_tokens = jnp.mean(exp_oh, axis=0)
    frac_router = jnp.mean(gates, axis=0)
    aux = n_exp * jnp.sum(frac_tokens * frac_router)
    return y.reshape(b, s, d).astype(x.dtype), aux
