"""GPipe-style pipeline parallelism over the ``pipe`` mesh axis.

The layer stack's leading depth axis (models/core.py ``stack_layers``) is
sharded over ``pipe``, so each stage holds depth/n_stages contiguous layers
in HBM — the memory-scaling lever. The batch is split into M microbatches;
activations hop stage-to-stage via ``lax.ppermute`` (point-to-point ICI) on
a schedule of M + n_stages - 1 ticks, and every tick every stage computes —
bubble fraction (n_stages-1)/(M+n_stages-1), the GPipe number.

Differentiable end-to-end (scan + ppermute), so one ``jax.grad`` over the
pipelined forward gives pipeline-parallel training without a hand-written
backward schedule.
"""

from __future__ import annotations

from functools import partial
from typing import Any, Callable

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

from rafiki_tpu.parallel.mesh import DATA_AXIS, PIPELINE_AXIS


def _stage_local(params_local: Any, x_mbs: jax.Array, *, block_fn,
                 axis_name: str, n_microbatches: int) -> jax.Array:
    """Per-stage body (inside shard_map).

    params_local: this stage's layer stack (L_local, ...).
    x_mbs: (M, mb, ...) full input microbatches (replicated; only stage 0
    reads them).
    """
    n = jax.lax.axis_size(axis_name)
    my = jax.lax.axis_index(axis_name)
    m = n_microbatches

    def apply_stage(x):
        def body(h, layer):
            return block_fn(layer, h), None
        h, _ = jax.lax.scan(body, x, params_local)
        return h

    fwd_perm = [(r, (r + 1) % n) for r in range(n)]
    mb_shape = x_mbs.shape[1:]

    def tick(carry, t):
        buf = carry  # activation arriving from the previous stage
        feed = x_mbs[jnp.minimum(t, m - 1)]
        inp = jnp.where(my == 0, feed, buf)
        out = apply_stage(inp)
        nxt = jax.lax.ppermute(out, axis_name, fwd_perm)
        return nxt, out

    t_total = m + n - 1
    _, outs = jax.lax.scan(tick, jnp.zeros(mb_shape, x_mbs.dtype),
                           jnp.arange(t_total))
    # the last stage emitted microbatch j at tick j + (n-1)
    y = outs[n - 1:]                      # (M, mb, ...)
    y = jnp.where(my == n - 1, y, 0.0)
    # broadcast the final activations to every stage
    return jax.lax.psum(y, axis_name)


def gpipe_apply(block_fn: Callable[[Any, jax.Array], jax.Array],
                stacked_params: Any, x: jax.Array, mesh: Mesh,
                n_microbatches: int,
                pipe_axis: str = PIPELINE_AXIS,
                data_axis: str = DATA_AXIS) -> jax.Array:
    """Run ``block_fn`` over the pipe-sharded layer stack with microbatched
    pipelining. ``x``: (B, ...) with B divisible by n_microbatches; layer
    stack depth divisible by the pipe axis size. If the mesh also has a
    ``data`` axis, the microbatch dim stays data-sharded (DP x PP compose:
    each data shard runs its own pipeline over the same stage weights)."""
    n_stages = mesh.shape[pipe_axis]
    b = x.shape[0]
    assert b % n_microbatches == 0, "batch must divide into microbatches"
    x_mbs = x.reshape(n_microbatches, b // n_microbatches, *x.shape[1:])

    # keep the microbatch dim data-sharded only when it divides; otherwise
    # fall back to replicated input (correct, just more ICI traffic)
    dp = data_axis if data_axis in mesh.axis_names else None
    if dp is not None and (b // n_microbatches) % mesh.shape[dp] != 0:
        dp = None
    x_spec = P(None, dp)
    param_specs = jax.tree.map(lambda _: P(pipe_axis), stacked_params)
    fn = jax.shard_map(
        partial(_stage_local, block_fn=block_fn, axis_name=pipe_axis,
                n_microbatches=n_microbatches),
        mesh=mesh,
        in_specs=(param_specs, x_spec),
        out_specs=x_spec,
        check_vma=False,
    )
    y = fn(stacked_params, x_mbs)
    return y.reshape(b, *y.shape[2:])
