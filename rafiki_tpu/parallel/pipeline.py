"""GPipe-style pipeline parallelism over the ``pipe`` mesh axis.

The layer stack's leading depth axis (models/core.py ``stack_layers``) is
sharded over ``pipe``, so each stage holds depth/n_stages contiguous layers
in HBM — the memory-scaling lever. The batch is split into M microbatches;
activations hop stage-to-stage via ``lax.ppermute`` (point-to-point ICI) on
a schedule of M + n_stages - 1 ticks, and every tick every stage computes —
bubble fraction (n_stages-1)/(M+n_stages-1), the GPipe number.

Differentiable end-to-end (scan + ppermute), so one ``jax.grad`` over the
pipelined forward gives pipeline-parallel training without a hand-written
backward schedule.
"""

from __future__ import annotations

from functools import partial
from typing import Any, Callable

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

from rafiki_tpu.parallel.mesh import DATA_AXIS, PIPELINE_AXIS
from rafiki_tpu.parallel.sharding import axis_size, shard_map


def _make_stage_apply(params_local: Any, block_fn):
    def apply_stage(x):
        def body(h, layer):
            return block_fn(layer, h), None
        h, _ = jax.lax.scan(body, x, params_local)
        return h
    return apply_stage


def _stage_local_streamed(params_local: Any, x_local: jax.Array, *, block_fn,
                          axis_name: str, n_microbatches: int) -> jax.Array:
    """Per-stage body with the input microbatches SHARDED over stages.

    x_local: (M'/n, mb, ...) — stage s starts holding queue slots
    [s*M'/n, (s+1)*M'/n), where M' is the microbatch count padded up to a
    multiple of the stage count (gpipe_apply pads; ``n_microbatches`` is
    the REAL count M and alone drives the tick schedule). The shards form
    one distributed queue in stage-major order; every tick it rotates one
    slot toward stage 0 (a backward ``ppermute`` of each stage's head), so
    stage 0's local head is always the next microbatch to feed. Input HBM
    per stage is O(B/n) instead of a replicated feed's O(B) — activation
    memory scales with pipeline depth like the weights do.

    The real microbatches occupy the first M queue slots, so ticks
    0..M-1 feed them in order; ticks past M feed padded/wrapped (dead)
    entries into stage 0, whose outputs can never reach the last stage
    before the M + n - 1 tick schedule ends, so they are never observed.
    """
    n = axis_size(axis_name)
    my = jax.lax.axis_index(axis_name)
    m = n_microbatches

    apply_stage = _make_stage_apply(params_local, block_fn)
    fwd_perm = [(r, (r + 1) % n) for r in range(n)]
    bwd_perm = [(r, (r - 1) % n) for r in range(n)]
    mb_shape = x_local.shape[1:]

    def tick(carry, _t):
        buf, queue = carry
        inp = jnp.where(my == 0, queue[0], buf)
        out = apply_stage(inp)
        nxt = jax.lax.ppermute(out, axis_name, fwd_perm)
        # rotate the distributed queue: my head goes to the previous
        # stage's tail; the next stage's head becomes my tail
        incoming = jax.lax.ppermute(queue[0], axis_name, bwd_perm)
        queue = jnp.concatenate([queue[1:], incoming[None]], axis=0)
        return (nxt, queue), out

    t_total = m + n - 1
    (_, _), outs = jax.lax.scan(
        tick, (jnp.zeros(mb_shape, x_local.dtype), x_local),
        jnp.arange(t_total))
    y = outs[n - 1:]                      # (M, mb, ...)
    y = jnp.where(my == n - 1, y, 0.0)
    return jax.lax.psum(y, axis_name)


def gpipe_apply(block_fn: Callable[[Any, jax.Array], jax.Array],
                stacked_params: Any, x: jax.Array, mesh: Mesh,
                n_microbatches: int,
                pipe_axis: str = PIPELINE_AXIS,
                data_axis: str = DATA_AXIS) -> jax.Array:
    """Run ``block_fn`` over the pipe-sharded layer stack with microbatched
    pipelining. ``x``: (B, ...) with B divisible by n_microbatches; layer
    stack depth divisible by the pipe axis size. If the mesh also has a
    ``data`` axis, the microbatch dim stays data-sharded (DP x PP compose:
    each data shard runs its own pipeline over the same stage weights)."""
    n_stages = mesh.shape[pipe_axis]
    b = x.shape[0]
    assert b % n_microbatches == 0, "batch must divide into microbatches"
    x_mbs = x.reshape(n_microbatches, b // n_microbatches, *x.shape[1:])

    # pad the queue (NOT the schedule) up to a multiple of the stage count
    # so the input microbatches always shard over stages — the padded
    # entries sit behind the real ones and are only ever fed on dead
    # ticks, so no extra compute reaches the output (see
    # _stage_local_streamed). This keeps input HBM at O(B/n) per stage
    # for every M, where a replicated-input fallback would cost O(B).
    pad = (-n_microbatches) % n_stages
    if pad:
        x_mbs = jnp.concatenate(
            [x_mbs, jnp.zeros((pad, *x_mbs.shape[1:]), x_mbs.dtype)], axis=0)

    # keep the microbatch dim data-sharded only when it divides
    dp = data_axis if data_axis in mesh.axis_names else None
    if dp is not None and (b // n_microbatches) % mesh.shape[dp] != 0:
        dp = None
    param_specs = jax.tree.map(lambda _: P(pipe_axis), stacked_params)
    fn = shard_map(
        partial(_stage_local_streamed, block_fn=block_fn,
                axis_name=pipe_axis, n_microbatches=n_microbatches),
        mesh=mesh,
        in_specs=(param_specs, P(pipe_axis, dp)),
        out_specs=P(None, dp),
        check_vma=False,
    )
    y = fn(stacked_params, x_mbs)
    return y.reshape(b, *y.shape[2:])
