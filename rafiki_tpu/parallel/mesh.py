"""Device-mesh construction with chip-affine sub-slicing.

The reference pins a worker to GPUs via ``CUDA_VISIBLE_DEVICES`` set by the
swarm placement layer (reference rafiki/container/docker_swarm.py:122-126).
The TPU analogue here: the placement layer grants an executor a *subset of
mesh devices* via the ``RAFIKI_VISIBLE_DEVICES`` env var (comma-separated
``jax.devices()`` indices), and every model builds its mesh through
``get_default_mesh()`` so trials running side-by-side on one host occupy
disjoint chips.

Mesh axes follow the scaling-book convention: ``data`` (DP) innermost-most
plentiful, ``model`` (TP) over fast ICI neighbours, plus optional ``seq`` (SP)
and ``expert`` (EP) axes for long-context / MoE models.
"""

from __future__ import annotations

import os
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence, Tuple

import jax
import numpy as np
from jax.sharding import Mesh

DATA_AXIS = "data"
MODEL_AXIS = "model"
SEQ_AXIS = "seq"
EXPERT_AXIS = "expert"
PIPELINE_AXIS = "pipe"


def visible_devices() -> List[jax.Device]:
    """Devices this process may use, honouring the placement layer's grant."""
    devices = jax.devices()
    spec = os.environ.get("RAFIKI_VISIBLE_DEVICES", "").strip()
    if not spec:
        return devices
    idxs = [int(s) for s in spec.split(",") if s.strip()]
    return [devices[i] for i in idxs]


@dataclass
class MeshSpec:
    """Declarative mesh shape. ``-1`` on one axis means "all remaining
    devices"."""

    axes: Dict[str, int] = field(default_factory=lambda: {DATA_AXIS: -1})

    def resolve(self, n_devices: int) -> Dict[str, int]:
        fixed = {k: v for k, v in self.axes.items() if v != -1}
        known = int(np.prod(list(fixed.values()))) if fixed else 1
        free = [k for k, v in self.axes.items() if v == -1]
        if len(free) > 1:
            raise ValueError("At most one mesh axis may be -1")
        out = dict(fixed)
        if free:
            if n_devices % known != 0:
                raise ValueError(
                    f"{n_devices} devices not divisible by fixed axes {fixed}"
                )
            out[free[0]] = n_devices // known
        elif known != n_devices:
            raise ValueError(f"Mesh {self.axes} needs {known} devices, have {n_devices}")
        # preserve declaration order
        return {k: out[k] for k in self.axes}


def make_mesh(
    spec: Optional[MeshSpec] = None, devices: Optional[Sequence[jax.Device]] = None
) -> Mesh:
    """Build a Mesh over the granted devices (default: pure data-parallel)."""
    devices = list(devices if devices is not None else visible_devices())
    spec = spec or MeshSpec()
    shape = spec.resolve(len(devices))
    arr = np.array(devices).reshape(tuple(shape.values()))
    return Mesh(arr, tuple(shape.keys()))


_default_mesh: Optional[Mesh] = None


def get_default_mesh() -> Mesh:
    """Process-wide default mesh over the granted devices (data axis only).
    Rebuilt if the device grant changed (tests flip RAFIKI_VISIBLE_DEVICES)."""
    global _default_mesh
    devs = visible_devices()
    if _default_mesh is None or list(_default_mesh.devices.flat) != devs:
        _default_mesh = make_mesh(devices=devs)
    return _default_mesh


def mesh_shape(mesh: Mesh) -> Tuple[int, ...]:
    return tuple(mesh.devices.shape)
