"""Device-mesh construction with chip-affine sub-slicing.

The reference pins a worker to GPUs via ``CUDA_VISIBLE_DEVICES`` set by the
swarm placement layer (reference rafiki/container/docker_swarm.py:122-126).
The TPU analogue here: the placement layer grants an executor thread a
*subset of mesh devices* via ``set_device_grant`` (thread-local, since
executors share one process), and every model builds its mesh through
``get_default_mesh()`` so trials running side-by-side on one host occupy
disjoint chips. The ``RAFIKI_VISIBLE_DEVICES`` env var (comma-separated
``jax.devices()`` indices) is the process-wide fallback for single-executor
deployments and tests.

Caveat: the grant is per-thread. Model code that spawns its own helper
threads must propagate it with ``set_device_grant(get_device_grant())`` in
the child thread, or the child sees all devices.

Mesh axes follow the scaling-book convention: ``data`` (DP) innermost-most
plentiful, ``model`` (TP) over fast ICI neighbours, plus optional ``seq`` (SP)
and ``expert`` (EP) axes for long-context / MoE models.
"""

from __future__ import annotations

import os
import threading
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence, Tuple

import jax
import numpy as np
from jax.sharding import Mesh

DATA_AXIS = "data"
MODEL_AXIS = "model"
SEQ_AXIS = "seq"
EXPERT_AXIS = "expert"
PIPELINE_AXIS = "pipe"

# Thread-local grant: executors run as threads sharing one process, so the
# env var (process-global) can't express per-trial chip affinity. The
# placement layer sets this at executor-thread start.
_thread_grant = threading.local()


def set_device_grant(indices: Optional[Sequence[int]]) -> None:
    """Restrict this thread's default devices to `indices` of jax.devices().
    ``None`` clears the grant."""
    _thread_grant.indices = tuple(indices) if indices else None


def get_device_grant() -> Optional[Tuple[int, ...]]:
    """This thread's device grant (for propagating into helper threads)."""
    return getattr(_thread_grant, "indices", None)


def visible_devices() -> List[jax.Device]:
    """Devices this thread may use: the thread grant if set, else the
    ``RAFIKI_VISIBLE_DEVICES`` env grant, else all devices."""
    devices = jax.devices()
    grant = getattr(_thread_grant, "indices", None)
    if grant:
        return [devices[i] for i in grant]
    spec = os.environ.get("RAFIKI_VISIBLE_DEVICES", "").strip()
    if not spec:
        return devices
    idxs = [int(s) for s in spec.split(",") if s.strip()]
    return [devices[i] for i in idxs]


@dataclass
class MeshSpec:
    """Declarative mesh shape. ``-1`` on one axis means "all remaining
    devices"."""

    axes: Dict[str, int] = field(default_factory=lambda: {DATA_AXIS: -1})

    def resolve(self, n_devices: int) -> Dict[str, int]:
        fixed = {k: v for k, v in self.axes.items() if v != -1}
        known = int(np.prod(list(fixed.values()))) if fixed else 1
        free = [k for k, v in self.axes.items() if v == -1]
        if len(free) > 1:
            raise ValueError("At most one mesh axis may be -1")
        out = dict(fixed)
        if free:
            if n_devices % known != 0:
                raise ValueError(
                    f"{n_devices} devices not divisible by fixed axes {fixed}"
                )
            out[free[0]] = n_devices // known
        elif known != n_devices:
            raise ValueError(f"Mesh {self.axes} needs {known} devices, have {n_devices}")
        # preserve declaration order
        return {k: out[k] for k in self.axes}


def make_mesh(
    spec: Optional[MeshSpec] = None, devices: Optional[Sequence[jax.Device]] = None
) -> Mesh:
    """Build a Mesh over the granted devices (default: pure data-parallel)."""
    devices = list(devices if devices is not None else visible_devices())
    spec = spec or MeshSpec()
    shape = spec.resolve(len(devices))
    arr = np.array(devices).reshape(tuple(shape.values()))
    return Mesh(arr, tuple(shape.keys()))


_default_mesh = threading.local()


def get_default_mesh() -> Mesh:
    """This thread's default mesh over its granted devices (data axis only).
    Rebuilt if the device grant changed (placement layer or test env)."""
    devs = visible_devices()
    cached: Optional[Mesh] = getattr(_default_mesh, "mesh", None)
    if cached is None or list(cached.devices.flat) != devs:
        cached = make_mesh(devices=devs)
        _default_mesh.mesh = cached
    return cached


def mesh_shape(mesh: Mesh) -> Tuple[int, ...]:
    return tuple(mesh.devices.shape)
