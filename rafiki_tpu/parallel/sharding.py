"""GSPMD training: pjit a whole train step over an explicit mesh.

Where the DataParallelTrainer (sdk/jax_backend.py) replicates params and
shards only the batch, this layer takes a *pytree of PartitionSpecs* from the
model (e.g. models/vit.py ``partition_specs``) and lets XLA place every
matmul and insert every collective (psum on row-parallel matmuls, all-gather
on seq-sharded attention) over ICI — the scaling-book recipe: pick a mesh,
annotate shardings, let XLA do the rest.

Spec trees may mention axes the current mesh doesn't have (``model``,
``seq``, ``pipe``, ``expert``); ``filter_pspec`` drops unknown axes so the
same model code runs on a pure-DP mesh, a dp×tp×sp mesh, or a single chip
without edits.
"""

from __future__ import annotations

import threading
from contextlib import contextmanager
from typing import Any, Callable, Dict, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np
import optax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

LossFn = Callable[[Any, Any, jax.Array], Tuple[jax.Array, Dict[str, jax.Array]]]


def shard_map(f: Callable, mesh: Mesh, in_specs: Any, out_specs: Any,
              check_vma: bool = True) -> Callable:
    """Version-compat resolver for ``jax.shard_map``.

    JAX promoted shard_map out of ``jax.experimental`` and renamed its
    replication-check kwarg (``check_rep`` -> ``check_vma``) across
    releases; this one helper pins the call sites (parallel/pipeline.py,
    parallel/ring.py, and any GspmdTrainer user composing manual
    collectives over this module's meshes) to a single resolution order:

    1. ``jax.shard_map(..., check_vma=...)`` — current API;
    2. ``jax.shard_map(..., check_rep=...)`` — the transitional top-level
       export that still used the old kwarg name;
    3. ``jax.experimental.shard_map.shard_map(..., check_rep=...)`` —
       the pre-promotion home (installed JAX 0.4.x).
    """
    top = getattr(jax, "shard_map", None)
    if top is not None:
        try:
            return top(f, mesh=mesh, in_specs=in_specs,
                       out_specs=out_specs, check_vma=check_vma)
        except TypeError:
            try:
                return top(f, mesh=mesh, in_specs=in_specs,
                           out_specs=out_specs, check_rep=check_vma)
            except TypeError:
                return top(f, mesh=mesh, in_specs=in_specs,
                           out_specs=out_specs)
    from jax.experimental.shard_map import shard_map as _shard_map

    return _shard_map(f, mesh=mesh, in_specs=in_specs,
                      out_specs=out_specs, check_rep=check_vma)


def axis_size(axis_name: str) -> int:
    """Compat twin of :func:`shard_map` for ``jax.lax.axis_size`` (absent
    pre-promotion): inside a shard_map body, ``psum(1, axis)`` of a Python
    literal constant-folds to the concrete axis size, so schedule loops
    (ring hop counts, pipeline ticks) stay Python ints either way."""
    fn = getattr(jax.lax, "axis_size", None)
    if fn is not None:
        return fn(axis_name)
    return jax.lax.psum(1, axis_name)


def filter_pspec(spec: P, mesh: Mesh) -> P:
    """Drop mesh-axis names the mesh doesn't define (so ``model``-sharded
    specs degrade to replicated on a pure-DP mesh, etc.)."""
    names = set(mesh.axis_names)

    def keep(entry):
        if entry is None:
            return None
        if isinstance(entry, (tuple, list)):
            kept = tuple(a for a in entry if a in names)
            return kept if kept else None
        return entry if entry in names else None

    return P(*(keep(e) for e in spec))


def named_shardings(mesh: Mesh, specs: Any) -> Any:
    """Pytree of PartitionSpec -> pytree of NamedSharding (axis-filtered)."""
    return jax.tree.map(
        lambda s: NamedSharding(mesh, filter_pspec(s, mesh)),
        specs,
        is_leaf=lambda x: isinstance(x, P),
    )


# -- activation-sharding hook ------------------------------------------------
# Models call ``shard_activations(x, ("data", "seq", None))`` at block
# boundaries; it is a no-op unless a trainer has installed its mesh here (so
# model code stays mesh-free). Thread-local because trial executors run as
# threads with different meshes (parallel/mesh.py device grants).

_act = threading.local()


@contextmanager
def activation_mesh(mesh: Optional[Mesh]):
    prev = getattr(_act, "mesh", None)
    _act.mesh = mesh
    try:
        yield
    finally:
        _act.mesh = prev


def shard_activations(x: jax.Array, axes: Sequence[Any]) -> jax.Array:
    mesh = getattr(_act, "mesh", None)
    if mesh is None:
        return x
    spec = filter_pspec(P(*axes), mesh)
    return jax.lax.with_sharding_constraint(x, NamedSharding(mesh, spec))


def current_mesh() -> Optional[Mesh]:
    """The mesh installed by the innermost ``activation_mesh`` (None outside
    a trainer). Models use this to route to mesh-aware paths — ring
    attention over the ``seq`` axis, GPipe over ``pipe`` — without the mesh
    appearing in their signatures.

    Trace-time contract: this is read during jit TRACING, so the routing it
    selects (and the mesh any shard_map binds) is baked into the compiled
    function. A jitted function must therefore be traced and executed under
    the same activation_mesh — keep one jitted closure per mesh, as
    GspmdTrainer does (its ``step``/``predict`` always wrap the per-instance
    jit in ``activation_mesh(self.mesh)``). Don't share one ``jax.jit``
    across different mesh contexts: the first trace's routing wins silently.
    """
    return getattr(_act, "mesh", None)


def mesh_axis_size(axis: str) -> int:
    """Size of ``axis`` on the current mesh (1 if absent / no mesh)."""
    mesh = current_mesh()
    if mesh is None or axis not in mesh.axis_names:
        return 1
    return mesh.shape[axis]


class GspmdTrainer:
    """pjit-style trainer: params sharded per the model's spec tree, batch
    sharded per ``batch_specs``, one fused donated train step.

    Optimizer state inherits its sharding from params via XLA propagation
    (the init is jitted with the param shardings as inputs), so optax states
    of any structure work without spec plumbing.
    """

    def __init__(
        self,
        loss_fn: LossFn,
        optimizer: optax.GradientTransformation,
        param_specs: Any,
        batch_specs: Any,
        mesh: Mesh,
        predict_fn: Optional[Callable[[Any, Any], jax.Array]] = None,
        predict_in_specs: Any = None,
        predict_out_specs: Any = None,
    ):
        self.mesh = mesh
        self.loss_fn = loss_fn
        self.optimizer = optimizer
        self.param_shardings = named_shardings(mesh, param_specs)
        self.batch_shardings = named_shardings(mesh, batch_specs)
        self._repl = NamedSharding(mesh, P())

        def train_step(params, opt_state, batch, rng):
            (loss, aux), grads = jax.value_and_grad(loss_fn, has_aux=True)(
                params, batch, rng
            )
            updates, opt_state = optimizer.update(grads, opt_state, params)
            params = optax.apply_updates(params, updates)
            # pin param output shardings so they never drift across steps
            params = jax.lax.with_sharding_constraint(
                params, self.param_shardings
            )
            return params, opt_state, loss, aux

        # params/opt_state shardings are taken from the arguments (committed
        # at init time); batch/rng pinned explicitly.
        self._train_step = jax.jit(train_step, donate_argnums=(0, 1))
        self.predict_fn = predict_fn
        if predict_fn is not None:
            # default: the predict input shards like the first train-batch
            # element (the common (x, y) -> x case)
            if predict_in_specs is None:
                leaves = jax.tree.leaves(
                    batch_specs, is_leaf=lambda s: isinstance(s, P))
                predict_in_specs = leaves[0] if leaves else P()
            self._predict_shardings = named_shardings(mesh, predict_in_specs)
            out_s = (
                named_shardings(mesh, predict_out_specs)
                if predict_out_specs is not None
                else None
            )
            self._predict = jax.jit(predict_fn, out_shardings=out_s)

    # -- lifecycle --------------------------------------------------------

    def init(self, init_fn: Callable[[jax.Array], Any], seed: int = 0
             ) -> Tuple[Any, Any]:
        """Shard-init params and optimizer state directly on the mesh (no
        host-side full materialization beyond the first trace)."""
        rng = jax.random.key(seed)
        with activation_mesh(self.mesh):
            params = jax.jit(
                init_fn, out_shardings=self.param_shardings)(rng)
            opt_state = jax.jit(self.optimizer.init)(params)
        return params, opt_state

    def step(self, params, opt_state, batch, rng):
        batch = jax.device_put(batch, self.batch_shardings)
        with activation_mesh(self.mesh):
            return self._train_step(params, opt_state, batch, rng)

    def predict(self, params, batch):
        assert self.predict_fn is not None
        batch = jax.device_put(batch, self._predict_shardings)
        with activation_mesh(self.mesh):
            return self._predict(params, batch)


def make_train_mesh(
    n_devices: Optional[int] = None,
    dp: Optional[int] = None,
    tp: int = 1,
    sp: int = 1,
    pp: int = 1,
    ep: int = 1,
    devices: Optional[Sequence[jax.Device]] = None,
) -> Mesh:
    """Build a (pipe, data, expert, seq, model) mesh.

    Axis order puts ``model`` innermost — TP traffic is the most
    latency-sensitive, so it rides nearest-neighbour ICI; ``pipe`` outermost
    (stage handoffs are point-to-point and tolerate the longest hops);
    ``data`` next (bandwidth-heavy psums amortize well). Unspecified dp
    absorbs the remaining devices.
    """
    from rafiki_tpu.parallel.mesh import visible_devices

    devs = list(devices if devices is not None else visible_devices())
    if n_devices is not None:
        devs = devs[:n_devices]
    n = len(devs)
    fixed = tp * sp * pp * ep
    if dp is None:
        if n % fixed:
            raise ValueError(f"{n} devices not divisible by tp*sp*pp*ep={fixed}")
        dp = n // fixed
    if dp * fixed != n:
        raise ValueError(f"dp*tp*sp*pp*ep={dp * fixed} != {n} devices")
    arr = np.array(devs).reshape(pp, dp, ep, sp, tp)
    return Mesh(arr, ("pipe", "data", "expert", "seq", "model"))
