"""Head 3 — the whole-package concurrency analyzer.

The platform is a deeply threaded control plane (autoscaler ticks,
rollout judges, recovery reconcilers, heartbeat monitors, drain paths,
three data-plane queue families), but PR 9's lock discipline (FWK301)
protects exactly the attributes someone annotated. This head needs **no
annotations**: it infers the locking protocol a class already follows
and reports the sites that break it — classic lockset analysis (Eraser)
plus compositional reasoning without whole-program aliasing (RacerD),
kept tractable as a pure AST pass so the PR 9 zero-untrusted-execution
contract holds.

Finding codes (catalog + how-to-fix recipes in docs/static-analysis.md):

- **CONC101 unguarded write** / **CONC102 unguarded read-in-decision**:
  for every ``self._attr`` shared across thread contexts, the guarding
  lock is inferred as the lock held at the *majority* of access sites;
  the unguarded minority sites are the findings. Escape analysis keeps
  the noise down: attributes touched only before any
  ``Thread(...).start()`` / executor submit in the class are
  thread-confined, and attributes never written after ``__init__`` are
  immutable-after-publication — both exempt.

- **CONC201 potential deadlock**: acquires-while-holding edges are
  collected package-wide (including through single-level direct
  ``self.method()`` calls — the same call-depth budget the POP003 taint
  pass uses — and through attributes whose class is statically known);
  a cycle in the graph is reported with one witness per edge. A
  non-reentrant ``Lock`` re-acquired while already held is the
  degenerate self-cycle.

- **CONC301 check-then-act** / **CONC302 read-modify-write**: for
  shared attributes with *no* inferable lock (the family lockset
  inference cannot help), ``if self._x: ... self._x = ...`` and
  ``self._x += ...`` / mutating container calls outside any lock scope
  are flagged — the two atomicity shapes the GIL does not make atomic.

Escape grammar (true negatives the inference cannot see — every
annotation carries a reason):

- ``# lint: thread-confined(reason)`` on an attribute's assignment (or
  on the ``class`` line for the whole class): the attribute never
  escapes to another thread.
- ``# lint: unguarded(reason)`` on an access line: this site is
  deliberately lock-free (shared with FWK301's grammar).
- ``# lint: lock-order(reason)`` on a ``with self._lock:`` line: the
  acquire-while-holding edges created inside this scope are deliberate.
- ``# guarded-by: <lock>`` on a ``def`` line (PR 9 grammar): callers
  hold the lock for the whole method body.
"""

from __future__ import annotations

import ast
import re
from typing import Dict, Iterable, List, Optional, Set, Tuple

from rafiki_tpu.analysis import astutil
from rafiki_tpu.analysis.findings import ERROR, WARN, Finding

_UNGUARDED_RE = re.compile(r"lint:\s*unguarded\s*\(")
_CONFINED_RE = re.compile(r"lint:\s*thread-confined\s*\(")
_LOCK_ORDER_RE = re.compile(r"lint:\s*lock-order\s*\(")
_GUARDED_BY_RE = re.compile(r"guarded-by:\s*([A-Za-z_][A-Za-z0-9_]*)")

#: threading primitives that ARE synchronization state — accesses to
#: these attributes are lock traffic, not shared-data traffic
_SYNC_CTORS = {"Event", "Semaphore", "BoundedSemaphore", "Barrier",
               "local"}
#: thread-safe containers/handles: mutating them needs no caller lock
_THREADSAFE_CTORS = {"Queue", "LifoQueue", "PriorityQueue", "SimpleQueue"}
#: attribute names that read as locks even when the assignment is out of
#: sight (e.g. inherited from a base class in another file)
_LOCKISH_NAME_RE = re.compile(r"lock|cond|mutex")
#: container method calls that mutate the receiver in place
_MUTATORS = {"append", "extend", "insert", "remove", "pop", "popleft",
             "appendleft", "clear", "add", "discard", "update",
             "setdefault", "popitem", "sort", "reverse"}

# access kinds
READ, WRITE, RMW = "read", "write", "rmw"

#: held-set entries for module-level locks carry this prefix so they can
#: never collide with a ``self.<attr>`` lock name
_MOD_PREFIX = "::"


def _display_lock(lock: str) -> str:
    return lock[len(_MOD_PREFIX):] if lock.startswith(_MOD_PREFIX) \
        else f"self.{lock}"


class _Access:
    __slots__ = ("attr", "kind", "line", "held", "decision", "method",
                 "exempt")

    def __init__(self, attr: str, kind: str, line: int,
                 held: frozenset, decision: bool, method: str,
                 exempt: bool) -> None:
        self.attr = attr
        self.kind = kind
        self.line = line
        self.held = held
        self.decision = decision
        self.method = method
        self.exempt = exempt


class _ClassSummary:
    """Everything the analyzer knows about one class definition."""

    def __init__(self, rel: str, node: ast.ClassDef,
                 module_locks: Set[str]) -> None:
        self.rel = rel
        self.node = node
        self.name = node.name
        self.module_locks = module_locks
        self.lock_attrs: Set[str] = set()     # created Lock/RLock/Condition
        self.rlock_attrs: Set[str] = set()    # reentrant subset
        self.lock_alias: Dict[str, str] = {}  # Condition(self._x) -> _x
        self.sync_attrs: Set[str] = set()     # Events, semaphores, queues
        self.confined_attrs: Set[str] = set()
        self.confined_class = False
        self.entry_methods: Set[str] = set()  # Thread targets / submits
        self.thread_reachable: Set[str] = set()
        self.spawns_threads = False
        self.calls: Dict[str, Set[str]] = {}  # method -> direct self calls
        self.methods: Set[str] = set()
        self.accesses: List[_Access] = []
        #: (held_lock, acquired_lock, line, method) nested-with edges
        self.acquires: List[Tuple[str, str, int, str]] = []
        #: method -> locks acquired directly anywhere in its own body
        self.method_acquires: Dict[str, Set[str]] = {}
        #: method -> (line, callee) single-level call sites w/ held locks
        self.held_calls: List[Tuple[frozenset, ast.Call, int, str]] = []
        #: attr -> class name it is an instance of (self._x = Foo(...))
        self.attr_types: Dict[str, str] = {}

    def canonical(self, lock: str) -> str:
        return self.lock_alias.get(lock, lock)

    def is_lock_attr(self, attr: str) -> bool:
        return attr in self.lock_attrs or (
            bool(_LOCKISH_NAME_RE.search(attr))
            and attr not in self.sync_attrs)


def _annotated(comments: Dict[int, str], line: int,
               pattern: re.Pattern) -> bool:
    return bool(pattern.search(comments.get(line, ""))
                or pattern.search(comments.get(line - 1, "")))


def _self_attr(node: ast.AST) -> Optional[str]:
    if isinstance(node, ast.Attribute) and isinstance(node.value, ast.Name) \
            and node.value.id == "self":
        return node.attr
    return None


def _self_attr_base(node: ast.AST) -> Optional[ast.Attribute]:
    """The ``self._x`` attribute under a chain of subscripts:
    ``self._x[k][j]`` -> the ``self._x`` node. Mutating an item of a
    shared container is a mutation the container's lock must cover."""
    while isinstance(node, ast.Subscript):
        node = node.value
    if _self_attr(node) is not None:
        return node  # type: ignore[return-value]
    return None


def _module_locks(tree: ast.Module) -> Set[str]:
    """Names assigned ``threading.Lock()``/``RLock()``/``Condition()`` at
    module level — shared by every instance in the process."""
    out: Set[str] = set()
    for node in tree.body:
        if isinstance(node, ast.Assign) and isinstance(node.value, ast.Call):
            ctor = astutil.terminal_name(node.value.func)
            if ctor in ("Lock", "RLock", "Condition"):
                for t in node.targets:
                    if isinstance(t, ast.Name):
                        out.add(t.id)
    return out


def _self_method_args(call: ast.Call, methods: Set[str]) -> Set[str]:
    """Methods of this class handed to a spawn call — ``target=self.m``,
    positional ``self.m``, or a lambda whose body calls ``self.m``."""
    out: Set[str] = set()
    candidates: List[ast.AST] = list(call.args)
    candidates.extend(kw.value for kw in call.keywords if kw.value)
    for arg in candidates:
        attr = _self_attr(arg)
        if attr is not None and attr in methods:
            out.add(attr)
        elif isinstance(arg, ast.Lambda):
            for n in ast.walk(arg.body):
                a = _self_attr(n)
                if a is not None and a in methods:
                    out.add(a)
    return out


def _is_spawn_call(call: ast.Call) -> bool:
    name = astutil.terminal_name(call.func)
    if name in ("Thread", "Timer"):
        return True
    return (isinstance(call.func, ast.Attribute)
            and call.func.attr in ("submit", "map")
            and bool(call.args))


def _decision_node_ids(stmt: ast.stmt) -> Set[int]:
    """ids of AST nodes in a *decision* position under ``stmt``: an
    If/While/IfExp/Assert test or a comprehension condition — a stale
    read there silently steers control flow (CONC102's shape)."""
    out: Set[int] = set()

    def mark(sub: Optional[ast.AST]) -> None:
        if sub is not None:
            for n in ast.walk(sub):
                out.add(id(n))

    for node in ast.walk(stmt):
        if isinstance(node, (ast.If, ast.While, ast.IfExp)):
            mark(node.test)
        elif isinstance(node, ast.Assert):
            mark(node.test)
        elif isinstance(node, ast.comprehension):
            for cond in node.ifs:
                mark(cond)
    return out


def _own_scope_walk(stmt: ast.stmt) -> Iterable[ast.AST]:
    """The statement's own expressions — nested statement bodies are
    separate lock scopes visited by the recursive walk, and nested
    function/class definitions run later on their own terms."""
    for field, value in ast.iter_fields(stmt):
        if field in ("body", "orelse", "finalbody", "handlers"):
            continue
        items = value if isinstance(value, list) else [value]
        for item in items:
            if isinstance(item, ast.AST):
                yield item
                yield from astutil.walk_no_nested_functions(item)


# -- phase 1: per-class summaries -------------------------------------------

def _summarize_class(rel: str, cls: ast.ClassDef,
                     comments: Dict[int, str],
                     module_locks: Set[str]) -> _ClassSummary:
    cs = _ClassSummary(rel, cls, module_locks)
    cs.confined_class = _annotated(comments, cls.lineno, _CONFINED_RE)
    methods = {n.name: n for n in cls.body
               if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))}
    cs.methods = set(methods)

    # class-level assignments (locks or typed attrs as class attributes)
    for node in cls.body:
        if isinstance(node, ast.Assign):
            for t in node.targets:
                if isinstance(t, ast.Name):
                    _classify_attr_assign(cs, t.id, node.value,
                                          node.lineno, comments)

    # first scan: attr classification, spawn sites, self-call graph
    for mname, mnode in methods.items():
        cs.calls[mname] = set()
        for node in astutil.walk_no_nested_functions(mnode):
            if isinstance(node, ast.Assign):
                for t in node.targets:
                    attr = _self_attr(t)
                    if attr is not None:
                        _classify_attr_assign(cs, attr, node.value,
                                              node.lineno, comments)
            elif isinstance(node, ast.Call):
                if _is_spawn_call(node):
                    targets = _self_method_args(node, cs.methods)
                    cs.entry_methods |= targets
                    # `.map` only counts with a self-method target —
                    # jax.tree.map and friends are not thread spawns
                    name = astutil.terminal_name(node.func)
                    if targets or name in ("Thread", "Timer", "submit"):
                        cs.spawns_threads = True
                func_attr = _self_attr(node.func)
                if func_attr is not None and func_attr in cs.methods:
                    cs.calls[mname].add(func_attr)
    # escape analysis: which methods can run on a spawned thread
    # (transitive closure over direct self calls from the entry methods)
    stack = list(cs.entry_methods)
    while stack:
        m = stack.pop()
        if m in cs.thread_reachable:
            continue
        cs.thread_reachable.add(m)
        stack.extend(cs.calls.get(m, ()))

    # second scan: accesses with lexical held-sets + acquire edges
    for mname, mnode in methods.items():
        _walk_method(cs, mname, mnode, comments)
    return cs


def _classify_attr_assign(cs: _ClassSummary, attr: str, value: ast.AST,
                          lineno: int, comments: Dict[int, str]) -> None:
    if _annotated(comments, lineno, _CONFINED_RE):
        cs.confined_attrs.add(attr)
    if not isinstance(value, ast.Call):
        return
    ctor = astutil.terminal_name(value.func)
    if ctor in ("Lock", "RLock"):
        cs.lock_attrs.add(attr)
        if ctor == "RLock":
            cs.rlock_attrs.add(attr)
    elif ctor == "Condition":
        cs.lock_attrs.add(attr)
        # Condition(self._x) wraps _x's very lock: holding either IS
        # holding the other, so both canonicalize to _x
        if value.args:
            inner = _self_attr(value.args[0])
            if inner is not None:
                cs.lock_alias[attr] = inner
    elif ctor in _SYNC_CTORS or ctor in _THREADSAFE_CTORS:
        cs.sync_attrs.add(attr)
    elif ctor is not None and ctor[:1].isupper():
        cs.attr_types.setdefault(attr, ctor)


def _with_locks(cs: _ClassSummary, stmt: ast.With) -> List[str]:
    """Locks a ``with`` statement acquires, in item order."""
    out: List[str] = []
    for item in stmt.items:
        expr = item.context_expr
        if isinstance(expr, ast.Call):
            expr = expr.func
        attr = _self_attr(expr)
        if attr is not None and cs.is_lock_attr(attr):
            out.append(cs.canonical(attr))
        elif isinstance(expr, ast.Name) and expr.id in cs.module_locks:
            out.append(_MOD_PREFIX + expr.id)
    return out


def _walk_method(cs: _ClassSummary, mname: str, mnode: ast.AST,
                 comments: Dict[int, str]) -> None:
    held0: Set[str] = set()
    # both lines checked independently — an unrelated comment on the
    # def line (# noqa) must not mask an annotation on the line above
    m = (_GUARDED_BY_RE.search(comments.get(mnode.lineno, ""))
         or _GUARDED_BY_RE.search(comments.get(mnode.lineno - 1, "")))
    if m:
        held0.add(cs.canonical(m.group(1)))
    cs.method_acquires.setdefault(mname, set())
    is_init = mname == "__init__"
    # within __init__, accesses BEFORE the first thread start are
    # thread-confined: nothing else can observe the half-built object
    state = {"started": not is_init}

    def visit_stmt(stmt: ast.stmt, held: Set[str]) -> None:
        decision_ids = _decision_node_ids(stmt)
        exempt_here = not state["started"]
        write_nodes: Set[int] = set()
        rmw_nodes: Set[int] = set()

        def mark_store(target: ast.AST, aug: bool) -> None:
            if isinstance(target, (ast.Tuple, ast.List)):
                for elt in target.elts:
                    mark_store(elt, aug)
                return
            if _self_attr(target) is not None:
                write_nodes.add(id(target))
                if aug:
                    rmw_nodes.add(id(target))
            elif isinstance(target, ast.Subscript):
                # self._x[k] = v / del self._x[k] / self._x[k][j] += v:
                # mutation of the container itself
                base = _self_attr_base(target)
                if base is not None:
                    write_nodes.add(id(base))
                    rmw_nodes.add(id(base))

        # the statement itself is part of its own scope: a top-level
        # Assign/AugAssign/Delete is where most stores live
        own_nodes = [stmt, *_own_scope_walk(stmt)]
        for node in own_nodes:
            if isinstance(node, ast.Assign):
                for t in node.targets:
                    mark_store(t, aug=False)
            elif isinstance(node, ast.AugAssign):
                mark_store(node.target, aug=True)
            elif isinstance(node, ast.Delete):
                for t in node.targets:
                    mark_store(t, aug=True)
            elif isinstance(node, ast.Call) \
                    and isinstance(node.func, ast.Attribute) \
                    and node.func.attr in _MUTATORS \
                    and _self_attr_base(node.func.value) is not None:
                base = _self_attr_base(node.func.value)
                write_nodes.add(id(base))
                rmw_nodes.add(id(base))
            elif isinstance(node, ast.Call):
                cs.held_calls.append(
                    (frozenset(held), node, node.lineno, mname))

        for node in own_nodes:
            attr = _self_attr(node)
            if attr is None or cs.is_lock_attr(attr) \
                    or attr in cs.sync_attrs:
                continue
            if id(node) in write_nodes:
                kind = RMW if id(node) in rmw_nodes else WRITE
            elif isinstance(node.ctx, (ast.Store, ast.Del)):
                kind = WRITE
            else:
                kind = READ
            exempt = exempt_here or _annotated(
                comments, node.lineno, _UNGUARDED_RE)
            cs.accesses.append(_Access(
                attr, kind, node.lineno, frozenset(held),
                kind == READ and id(node) in decision_ids, mname, exempt))

    def walk(body: List[ast.stmt], held: Set[str]) -> None:
        for stmt in body:
            if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef,
                                 ast.ClassDef)):
                continue
            if isinstance(stmt, ast.With):
                visit_stmt(stmt, held)
                inner = set(held)
                skip_edges = _annotated(comments, stmt.lineno,
                                        _LOCK_ORDER_RE)
                for lock in _with_locks(cs, stmt):
                    cs.method_acquires[mname].add(lock)
                    if not skip_edges:
                        for h in inner:
                            cs.acquires.append((h, lock, stmt.lineno,
                                                mname))
                    inner.add(lock)
                walk(stmt.body, inner)
                continue
            # the spawn may sit anywhere in the statement — the
            # dominant executor idiom ASSIGNS the future
            # (self._fut = pool.submit(self._run)), so scan the whole
            # own-scope, and flip BEFORE visiting: writes sharing the
            # spawn's statement are already observable by the thread
            if not state["started"] and any(
                    isinstance(n, ast.Call) and _starts_thread(n)
                    for n in _own_scope_walk(stmt)):
                state["started"] = True
            visit_stmt(stmt, held)
            for field in ("body", "orelse", "finalbody"):
                sub = getattr(stmt, field, None)
                if isinstance(sub, list) and sub \
                        and isinstance(sub[0], ast.stmt):
                    walk(sub, held)
            for handler in getattr(stmt, "handlers", []) or []:
                walk(handler.body, held)

    def _starts_thread(call: ast.Call) -> bool:
        return (isinstance(call.func, ast.Attribute)
                and call.func.attr == "start") or _is_spawn_call(call)

    walk(list(mnode.body), held0)


# -- phase 2: lockset + atomicity verdicts ----------------------------------

def _shared_attrs(cs: _ClassSummary) -> Set[str]:
    """Attributes plausibly reachable from more than one thread.

    - a class that spawns threads shares every attribute accessed both
      from a thread-entry-reachable method and from a caller-context
      method (the escape analysis);
    - a class that owns a lock but spawns nothing (a library object
      handed between threads — every queue family) shares every
      attribute the class itself locks somewhere (the lock is the
      author's own declaration of sharing), plus every attribute some
      method *container-mutates* while another method touches it — the
      compound-structure traffic (deque/dict/list mutation racing
      iteration) that raises at runtime even under the GIL;
    - either way, an attribute never *written* outside ``__init__`` is
      immutable-after-publication, and exempt.
    """
    by_attr: Dict[str, List[_Access]] = {}
    for a in cs.accesses:
        by_attr.setdefault(a.attr, []).append(a)
    shared: Set[str] = set()
    for attr, accs in by_attr.items():
        if attr in cs.confined_attrs:
            continue
        live = [a for a in accs
                if not (a.method == "__init__" and a.exempt)]
        if not any(a.kind in (WRITE, RMW) and a.method != "__init__"
                   for a in live):
            continue
        contexts = {("thread" if a.method in cs.thread_reachable
                     else "caller") for a in live}
        locked_somewhere = any(a.held for a in live)
        container_rmw = any(a.kind == RMW for a in live
                            if a.method != "__init__")
        if cs.spawns_threads and len(contexts) >= 2:
            shared.add(attr)
        elif cs.entry_methods and any(
                a.kind == RMW and a.method in cs.thread_reachable
                for a in live):
            # an entry method is not necessarily spawned ONCE: one
            # listener/sender/reporter thread per job/queue/replica is
            # the platform's normal shape, and sibling threads of the
            # same entry lose updates against each other exactly like
            # two different contexts would
            shared.add(attr)
        elif (locked_somewhere or container_rmw) \
                and len({a.method for a in live}) >= 2:
            shared.add(attr)
    return shared


def _infer_lock(accs: List[_Access]) -> Optional[Tuple[str, int, int]]:
    """(lock, covered, total) for the lock held at a strict majority —
    and at least two — of the non-exempt access sites, else None."""
    sites = [a for a in accs if not a.exempt]
    if not sites:
        return None
    counts: Dict[str, int] = {}
    for a in sites:
        for lock in a.held:
            counts[lock] = counts.get(lock, 0) + 1
    if not counts:
        return None
    lock = max(sorted(counts), key=lambda k: counts[k])
    covered = counts[lock]
    if covered < 2 or covered * 2 <= len(sites):
        return None
    return lock, covered, len(sites)


def _lockset_findings(cs: _ClassSummary,
                      shared: Set[str]) -> List[Finding]:
    findings: List[Finding] = []
    by_attr: Dict[str, List[_Access]] = {}
    for a in cs.accesses:
        by_attr.setdefault(a.attr, []).append(a)
    for attr in sorted(shared):
        accs = by_attr[attr]
        inferred = _infer_lock(accs)
        if inferred is None:
            findings.extend(_atomicity_findings(cs, attr, accs))
            continue
        lock, covered, total = inferred
        disp = _display_lock(lock)
        for a in accs:
            if a.exempt or lock in a.held:
                continue
            where = (f"{cs.name}.{attr} is guarded by {disp} at "
                     f"{covered}/{total} sites")
            if a.kind in (WRITE, RMW):
                findings.append(Finding(
                    "CONC101",
                    f"{where} — this write in {a.method}() races them; "
                    f"move it under 'with {disp}:' or annotate "
                    "'# lint: unguarded(reason)'",
                    ERROR, cs.rel, a.line))
            elif a.decision:
                findings.append(Finding(
                    "CONC102",
                    f"{where} — this read in {a.method}() steers a "
                    "branch on a possibly-stale value; snapshot it "
                    f"under 'with {disp}:' or annotate "
                    "'# lint: unguarded(reason)'",
                    WARN, cs.rel, a.line))
    return findings


def _atomicity_findings(cs: _ClassSummary, attr: str,
                        accs: List[_Access]) -> List[Finding]:
    """CONC301/302 for a shared attribute with no inferable lock."""
    findings: List[Finding] = []
    consumed: Set[int] = set()
    by_method: Dict[str, List[_Access]] = {}
    for a in accs:
        by_method.setdefault(a.method, []).append(a)
    method_nodes = {n.name: n for n in cs.node.body
                    if isinstance(n, (ast.FunctionDef,
                                      ast.AsyncFunctionDef))}
    for mname, maccs in sorted(by_method.items()):
        mnode = method_nodes.get(mname)
        if mnode is None:
            continue
        by_line = {a.line: a for a in maccs}
        for node in astutil.walk_no_nested_functions(mnode):
            if not isinstance(node, ast.If):
                continue
            test_reads = [n for n in ast.walk(node.test)
                          if _self_attr(n) == attr and isinstance(
                              getattr(n, "ctx", None), ast.Load)]
            if not test_reads:
                continue
            test_acc = by_line.get(test_reads[0].lineno)
            if test_acc is None or test_acc.held or test_acc.exempt:
                continue
            end = _subtree_end(node)
            writes = [a for a in maccs
                      if a.kind in (WRITE, RMW) and not a.held
                      and not a.exempt and node.lineno < a.line <= end]
            if writes:
                findings.append(Finding(
                    "CONC301",
                    f"check-then-act on {cs.name}.{attr} in {mname}(): "
                    "the test and the write are separate critical "
                    "sections, so another thread can interleave "
                    "between them; take one lock around both or "
                    "annotate '# lint: unguarded(reason)'",
                    WARN, cs.rel, node.lineno))
                consumed.add(test_acc.line)
                consumed.update(w.line for w in writes)
                break  # one check-then-act per method per attr
    for a in accs:
        if a.kind == RMW and not a.held and not a.exempt \
                and a.line not in consumed:
            findings.append(Finding(
                "CONC302",
                f"read-modify-write of shared {cs.name}.{attr} in "
                f"{a.method}() outside any lock — augmented assignment "
                "and container mutation are not atomic across threads; "
                "guard it or annotate '# lint: unguarded(reason)'",
                WARN, cs.rel, a.line))
    return findings


def _subtree_end(node: ast.AST) -> int:
    return max((getattr(n, "lineno", 0) for n in ast.walk(node)),
               default=getattr(node, "lineno", 0))


# -- phase 3: the package-wide lock-order graph -----------------------------

_Node = Tuple[str, str]  # (owner: class name or @module-rel, lock name)


class _LockGraph:
    def __init__(self) -> None:
        self.edges: Dict[_Node, Dict[_Node, Tuple[str, int, str]]] = {}
        self.rlocks: Set[_Node] = set()

    def add(self, src: _Node, dst: _Node,
            witness: Tuple[str, int, str]) -> None:
        self.edges.setdefault(src, {}).setdefault(dst, witness)


def _lock_node(cs: _ClassSummary, lock: str) -> _Node:
    if lock.startswith(_MOD_PREFIX):
        return ("@" + cs.rel, lock[len(_MOD_PREFIX):])
    return (cs.name, lock)


def _build_lock_graph(summaries: List[_ClassSummary]) -> _LockGraph:
    graph = _LockGraph()
    by_name: Dict[str, _ClassSummary] = {}
    for cs in summaries:
        by_name.setdefault(cs.name, cs)
        for lock in cs.rlock_attrs:
            graph.rlocks.add((cs.name, cs.canonical(lock)))
    for cs in summaries:
        for held, acquired, line, mname in cs.acquires:
            graph.add(_lock_node(cs, held), _lock_node(cs, acquired),
                      (cs.rel, line, f"{cs.name}.{mname}"))
        # one-level call inlining: while holding H, `self.m()` acquires
        # whatever m acquires directly; `self._x.m()` (where _x's class
        # is statically known) acquires what THAT m acquires
        for held, call, line, mname in cs.held_calls:
            if not held:
                continue
            callee_attr = _self_attr(call.func)
            if callee_attr is not None and callee_attr in cs.methods:
                target_cs, target_m = cs, callee_attr
            elif isinstance(call.func, ast.Attribute):
                recv = _self_attr(call.func.value)
                target_cs = by_name.get(cs.attr_types.get(recv or "", ""))
                target_m = call.func.attr
                if target_cs is None:
                    continue
            else:
                continue
            for lock in sorted(target_cs.method_acquires.get(target_m,
                                                             ())):
                dst = _lock_node(target_cs, lock)
                for h in sorted(held):
                    src = _lock_node(cs, h)
                    label = (f"{cs.name}.{mname} -> "
                             f"{target_cs.name}.{target_m}()")
                    graph.add(src, dst, (cs.rel, line, label))
    return graph


def _cycle_findings(graph: _LockGraph) -> List[Finding]:
    findings: List[Finding] = []
    # self-deadlock: a non-reentrant lock re-acquired while held
    for src in sorted(graph.edges):
        dsts = graph.edges[src]
        if src in dsts and src not in graph.rlocks:
            rel, line, where = dsts[src]
            findings.append(Finding(
                "CONC201",
                f"non-reentrant lock {src[0]}.{src[1]} is acquired "
                f"while already held ({where}) — the thread deadlocks "
                "against itself; drop the inner acquire, make the "
                "callee a '# guarded-by:' helper, or annotate the "
                "acquire '# lint: lock-order(reason)'",
                ERROR, rel, line))
    # ordering cycles (the AB/BA shape and longer) via bounded DFS
    seen_cycles: Set[frozenset] = set()
    for start in sorted(graph.edges):
        stack: List[Tuple[_Node, List[_Node]]] = [(start, [start])]
        while stack:
            node, path = stack.pop()
            for nxt in sorted(graph.edges.get(node, {})):
                if nxt == start and len(path) > 1:
                    key = frozenset(path)
                    if key in seen_cycles:
                        continue
                    seen_cycles.add(key)
                    hops = path + [start]
                    witnesses = [
                        f"{a[0]}.{a[1]} -> {b[0]}.{b[1]} at "
                        f"{graph.edges[a][b][0]}:{graph.edges[a][b][1]} "
                        f"({graph.edges[a][b][2]})"
                        for a, b in zip(hops, hops[1:])]
                    rel, line, _ = graph.edges[hops[0]][hops[1]]
                    findings.append(Finding(
                        "CONC201",
                        "lock-order cycle — threads taking these locks "
                        "in opposite orders deadlock: "
                        + "; ".join(witnesses)
                        + ". Make every path acquire in one canonical "
                        "order, or annotate the deliberate acquire "
                        "'# lint: lock-order(reason)'",
                        ERROR, rel, line))
                elif nxt not in path and len(path) < 6:
                    stack.append((nxt, path + [nxt]))
    return findings


# -- entry points -----------------------------------------------------------

def analyze_modules(
        modules: Dict[str, Tuple[ast.Module, str, Dict[int, str]]],
) -> List[Finding]:
    """Run the concurrency head over pre-parsed modules ({rel: (tree,
    source, comment_map)} — the shape framework.lint_package loads).
    Returns findings sorted by (file, line)."""
    summaries: List[_ClassSummary] = []
    for rel, (tree, _source, comments) in sorted(modules.items()):
        mod_locks = _module_locks(tree)
        for node in ast.walk(tree):
            if isinstance(node, ast.ClassDef):
                summaries.append(
                    _summarize_class(rel, node, comments, mod_locks))
    relevant = [cs for cs in summaries
                if (cs.spawns_threads or cs.lock_attrs)
                and not cs.confined_class]
    findings: List[Finding] = []
    for cs in relevant:
        findings.extend(_lockset_findings(cs, _shared_attrs(cs)))
    findings.extend(_cycle_findings(_build_lock_graph(relevant)))
    findings.sort(key=lambda f: (f.file, f.line, f.code))
    return findings


def analyze_package(root: Optional[str] = None) -> List[Finding]:
    """Load and analyze a whole package tree (the doctor's
    concurrency-lint check and ad-hoc use)."""
    from rafiki_tpu.analysis import framework

    root = root or framework.package_root()
    parse_errors: List[Finding] = []
    modules = framework._load_modules(root, parse_errors)
    return parse_errors + analyze_modules(modules)


def analyze_source(source: str, filename: str = "<memory>"
                   ) -> List[Finding]:
    """Single-file entry point (tests and the fixture corpus)."""
    tree = ast.parse(source, filename=filename)
    return analyze_modules(
        {filename: (tree, source, astutil.comment_map(source))})
