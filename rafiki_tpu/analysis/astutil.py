"""Shared AST plumbing for both analysis heads.

Everything here is pure syntax work — no uploaded code is ever imported
or executed (the whole point of verifying at upload time instead of
burning a trial to find out)."""

from __future__ import annotations

import ast
import io
import sys
import tokenize
from typing import Dict, Iterable, List, Optional, Set, Tuple

#: stdlib top-level module names (py3.10+); the fallback set keeps the
#: analyzer usable on older interpreters without claiming completeness
STDLIB_MODULES: Set[str] = set(getattr(sys, "stdlib_module_names", ()) or (
    "abc os sys re json math time random types typing itertools functools "
    "collections dataclasses tempfile threading logging io struct base64 "
    "hashlib pickle copy string textwrap traceback inspect importlib "
    "contextlib warnings enum uuid datetime pathlib queue".split()))


def parse(source: str, filename: str = "<uploaded>") -> ast.Module:
    """ast.parse that callers wrap for the typed TPL005 finding."""
    return ast.parse(source, filename=filename)


def comment_map(source: str) -> Dict[int, str]:
    """{lineno: comment text (without '#')} for every comment token.

    The ast module drops comments, but both annotation grammars
    (``# lint: absorb(...)``, ``# guarded-by: ...``) live in comments —
    tokenize recovers them without regex-over-strings false hits."""
    out: Dict[int, str] = {}
    try:
        for tok in tokenize.generate_tokens(io.StringIO(source).readline):
            if tok.type == tokenize.COMMENT:
                out[tok.start[0]] = tok.string.lstrip("#").strip()
    except (tokenize.TokenError, IndentationError, SyntaxError):
        # a half-parseable file still gets best-effort comments
        pass
    return out


def terminal_name(node: ast.AST) -> Optional[str]:
    """The final identifier of a Name/Attribute chain: ``jax.numpy.sum``
    -> ``sum``; ``jit`` -> ``jit``."""
    if isinstance(node, ast.Attribute):
        return node.attr
    if isinstance(node, ast.Name):
        return node.id
    return None


def dotted_name(node: ast.AST) -> Optional[str]:
    """Full dotted chain for Name/Attribute, else None:
    ``np.random.seed`` -> "np.random.seed"."""
    parts: List[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


def root_name(node: ast.AST) -> Optional[str]:
    """First identifier of a Name/Attribute chain (``np`` of
    ``np.random.seed``)."""
    while isinstance(node, ast.Attribute):
        node = node.value
    return node.id if isinstance(node, ast.Name) else None


def is_constant(node: ast.AST) -> bool:
    """A value the platform can evaluate without running user code:
    constants, +-constants, and containers of such."""
    if isinstance(node, ast.Constant):
        return True
    if isinstance(node, ast.UnaryOp) and isinstance(
            node.op, (ast.USub, ast.UAdd)):
        return is_constant(node.operand)
    if isinstance(node, (ast.List, ast.Tuple, ast.Set)):
        return all(is_constant(e) for e in node.elts)
    if isinstance(node, ast.Dict):
        return all(k is not None and is_constant(k) for k in node.keys) and \
            all(is_constant(v) for v in node.values)
    if isinstance(node, ast.BinOp) and isinstance(
            node.op, (ast.Add, ast.Sub, ast.Mult, ast.Div, ast.Pow)):
        return is_constant(node.left) and is_constant(node.right)
    return False


_BINOPS = {ast.Add: lambda a, b: a + b, ast.Sub: lambda a, b: a - b,
           ast.Mult: lambda a, b: a * b, ast.Div: lambda a, b: a / b,
           ast.Pow: lambda a, b: a ** b}


def literal_value(node: ast.AST):
    """Evaluate exactly what :func:`is_constant` accepts — including the
    arithmetic BinOps ast.literal_eval refuses (``2 ** 10``); raises
    ValueError when not constant (callers treat that as non-literal)."""
    if isinstance(node, ast.BinOp) and type(node.op) in _BINOPS:
        try:
            return _BINOPS[type(node.op)](literal_value(node.left),
                                          literal_value(node.right))
        except (TypeError, ZeroDivisionError) as e:
            raise ValueError(f"unevaluable constant expression: {e}")
    if isinstance(node, (ast.List, ast.Tuple, ast.Set)):
        values = [literal_value(e) for e in node.elts]
        return {ast.List: list, ast.Tuple: tuple,
                ast.Set: set}[type(node)](values)
    if isinstance(node, ast.Dict):
        return {literal_value(k): literal_value(v)
                for k, v in zip(node.keys, node.values)}
    return ast.literal_eval(node)


def walk_no_nested_functions(node: ast.AST) -> Iterable[ast.AST]:
    """Walk a function body WITHOUT descending into nested function/class
    definitions — their bodies are separate analysis scopes."""
    stack: List[ast.AST] = list(ast.iter_child_nodes(node))
    while stack:
        n = stack.pop()
        yield n
        if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef,
                          ast.ClassDef, ast.Lambda)):
            continue
        stack.extend(ast.iter_child_nodes(n))


def _is_main_guard(node: ast.AST) -> bool:
    """``if __name__ == "__main__":`` — the local dev harness block;
    nothing under it runs in a worker."""
    if not isinstance(node, ast.If) or not isinstance(node.test,
                                                     ast.Compare):
        return False
    parts = [node.test.left] + list(node.test.comparators)
    names = {p.id for p in parts if isinstance(p, ast.Name)}
    consts = {p.value for p in parts if isinstance(p, ast.Constant)}
    return "__name__" in names and "__main__" in consts


def _catches_import_error(node: ast.AST) -> bool:
    """A Try whose handlers catch ImportError/ModuleNotFoundError — the
    optional-dependency idiom; imports under it degrade gracefully."""
    if not isinstance(node, ast.Try):
        return False
    for handler in node.handlers:
        if handler.type is None:
            return True
        types = handler.type.elts if isinstance(handler.type, ast.Tuple) \
            else [handler.type]
        if any(terminal_name(t) in ("ImportError", "ModuleNotFoundError",
                                    "Exception") for t in types):
            return True
    return False


def imported_top_modules(tree: ast.Module,
                         include_guarded: bool = False) -> Dict[str, int]:
    """{top-level module name: first lineno} over every import the
    WORKER would execute — including function-local imports, but not
    the ``if __name__ == "__main__":`` dev-harness block and not
    imports inside a try/except-ImportError optional-dependency
    fallback. ``include_guarded=True`` keeps both (the sandbox-policy
    pass must see imports a hostile template hides behind a guard)."""
    out: Dict[str, int] = {}
    stack: List[ast.AST] = list(ast.iter_child_nodes(tree))
    while stack:
        node = stack.pop()
        if not include_guarded and (_is_main_guard(node)
                                    or _catches_import_error(node)):
            continue
        if isinstance(node, ast.Import):
            for alias in node.names:
                out.setdefault(alias.name.split(".")[0], node.lineno)
        elif isinstance(node, ast.ImportFrom) and node.level == 0 \
                and node.module:
            out.setdefault(node.module.split(".")[0], node.lineno)
        stack.extend(ast.iter_child_nodes(node))
    return out


def class_map(tree: ast.Module) -> Dict[str, ast.ClassDef]:
    return {n.name: n for n in tree.body if isinstance(n, ast.ClassDef)}


def is_model_subclass(cls: ast.ClassDef,
                      classes: Dict[str, ast.ClassDef]) -> bool:
    """Does ``cls`` descend (within this file) from a base whose terminal
    name is BaseModel? Covers ``BaseModel``, ``model.BaseModel``, and
    local intermediate bases."""
    seen: Set[str] = set()
    stack = [cls]
    while stack:
        c = stack.pop()
        if c.name in seen:
            continue
        seen.add(c.name)
        for base in c.bases:
            name = terminal_name(base)
            if name == "BaseModel":
                return True
            if name in classes:
                stack.append(classes[name])
    return False


def own_and_inherited_methods(
        cls: ast.ClassDef, classes: Dict[str, ast.ClassDef]
) -> Dict[str, ast.FunctionDef]:
    """Method name -> FunctionDef, following bases defined in the same
    file (nearest definition wins, like the MRO would)."""
    out: Dict[str, ast.FunctionDef] = {}
    seen: Set[str] = set()
    stack = [cls]
    order: List[ast.ClassDef] = []
    while stack:
        c = stack.pop(0)
        if c.name in seen:
            continue
        seen.add(c.name)
        order.append(c)
        for base in c.bases:
            name = terminal_name(base)
            if name in classes:
                stack.append(classes[name])
    for c in order:
        for node in c.body:
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                out.setdefault(node.name, node)
    return out


def class_attr_assign(
        cls: ast.ClassDef, classes: Dict[str, ast.ClassDef], attr: str
) -> Optional[ast.AST]:
    """The value expression of a class-level ``attr = ...`` assignment,
    following same-file bases (nearest wins)."""
    seen: Set[str] = set()
    stack = [cls]
    while stack:
        c = stack.pop(0)
        if c.name in seen:
            continue
        seen.add(c.name)
        for node in c.body:
            targets: List[ast.expr] = []
            if isinstance(node, ast.Assign):
                targets = node.targets
            elif isinstance(node, ast.AnnAssign) and node.value is not None:
                targets = [node.target]
            for t in targets:
                if isinstance(t, ast.Name) and t.id == attr:
                    return node.value
        for base in c.bases:
            name = terminal_name(base)
            if name in classes:
                stack.append(classes[name])
    return None


def contains(node: ast.AST, predicate) -> Optional[ast.AST]:
    """First descendant (or the node itself) matching ``predicate``."""
    for n in ast.walk(node):
        if predicate(n):
            return n
    return None
