"""CLI for pre-upload local use and CI.

    python -m rafiki_tpu.analysis MODEL_FILE [CLASS_NAME] [--json]
        Run the template verifier; exit 1 when it finds anything
        (errors OR warnings — the local loop wants the full list).

    python -m rafiki_tpu.analysis --self-lint [--json]
        Run the framework self-lint AND the whole-package concurrency
        analyzer (lockset inference, lock-order cycles, atomicity)
        over the installed rafiki_tpu package; exit 1 on any finding
        (what tier-1 enforces).
"""

from __future__ import annotations

import json
import sys
from typing import List

from rafiki_tpu.analysis import lint_package, verify_template_source
from rafiki_tpu.analysis.findings import Finding


def _print_findings(findings: List[Finding], as_json: bool) -> None:
    if as_json:
        print(json.dumps([f.to_dict() for f in findings], indent=2))
    else:
        for f in findings:
            print(str(f))


def main(argv: List[str]) -> int:
    args = [a for a in argv if a != "--json"]
    as_json = "--json" in argv
    if not args or args[0] in ("-h", "--help"):
        print(__doc__)
        return 0 if args and args[0] in ("-h", "--help") else 2
    if args[0] == "--self-lint":
        findings = lint_package()
        _print_findings(findings, as_json)
        if not as_json:
            print(f"self-lint: {len(findings)} finding(s)")
        return 1 if findings else 0
    path = args[0]
    class_name = args[1] if len(args) > 1 else None
    try:
        with open(path, "r", encoding="utf-8") as f:
            source = f.read()
    except (OSError, UnicodeDecodeError) as e:
        print(f"cannot read {path}: {e}", file=sys.stderr)
        return 2
    report = verify_template_source(source, class_name, filename=path)
    if as_json:
        print(json.dumps(report.to_dict(), indent=2))
    else:
        _print_findings(report.findings, as_json=False)
        cap = ("population-capable"
               if report.capabilities.get("population") else "scalar")
        print(f"{path} [{report.class_name or '?'}]: {report.summary()} "
              f"({cap})")
    return 1 if report.findings else 0


if __name__ == "__main__":
    sys.exit(main(sys.argv[1:]))
