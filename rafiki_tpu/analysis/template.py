"""Head 1 — the template verifier: an AST pass pipeline over uploaded
model source. Zero untrusted code runs here; everything is syntax.

The reference validated uploads by dynamically loading the class
(reference model/model.py:244-273) — which executes module top-level
code and only proves the class *imports*. These passes prove the things
that otherwise burn a trial (or a chip-hour) to discover:

- structural contract: the six required BaseModel methods exist,
  ``get_knob_config`` is a real @staticmethod whose return value is
  *literally evaluable* (the advisor needs the space without running
  user code), declared ``dependencies`` cover every non-platform import;
- PopulationSpec consistency for the vmapped trial path (PR-8):
  ``dynamic_knobs`` ⊆ knob config, all three ``*_population`` methods
  overridden, and no Python branching on a dynamic knob inside the
  train path (members of one program must share one trace);
- JAX tracing pitfalls inside jit/vmap-reachable code: host syncs
  (``.item()``/``float()``/``np.asarray``), mutation of ``self`` under
  trace, and the legacy global ``numpy.random`` API;
- sandbox policy: imports the jail would refuse anyway fail at upload.

The report's ``capabilities`` dict is the single static capability
oracle — :func:`static_population_capability` replaces doctor.py's old
``b"population_spec" in bytes`` source sniff.
"""

from __future__ import annotations

import ast
from typing import Any, Dict, List, Optional, Set, Tuple

from rafiki_tpu.analysis import astutil
from rafiki_tpu.analysis.findings import ERROR, WARN, VerificationReport

REQUIRED_METHODS = ("get_knob_config", "train", "evaluate", "predict",
                    "dump_parameters", "load_parameters")
POPULATION_METHODS = ("train_population", "evaluate_population",
                      "dump_member_parameters")

#: knob constructors the advisor ships (sdk/knob.py); anything else
#: named ``*Knob`` is accepted too so templates can subclass BaseKnob
KNOWN_KNOB_CLASSES = {"IntegerKnob", "FloatKnob", "CategoricalKnob",
                      "FixedKnob"}

#: modules every worker environment provides without declaration: the
#: stdlib, the platform package itself, and the baked jax_graft
#: toolchain (mirrors sdk/deps.py's notion of "already importable")
IMPLICIT_MODULES = astutil.STDLIB_MODULES | {
    "rafiki_tpu", "numpy", "jax", "jaxlib", "optax"}

#: imports the sandbox (sdk/sandbox.py) exists to contain — a template
#: that needs these is hostile or misdesigned, and upload is the
#: cheapest place to say so. ``socket`` stays allowed: the default
#: jail shares the host netns (the TPU tunnel needs sockets) and
#: tests/test_sandbox.py documents that boundary.
FORBIDDEN_IMPORTS = {"subprocess", "ctypes", "pty", "resource", "pwd",
                     "grp", "setuptools", "pip", "ensurepip"}

#: pip-name -> import-name exceptions for the dependency check
_DIST_TO_IMPORT = {"scikit-learn": "sklearn", "pillow": "PIL",
                   "opencv-python": "cv2", "pyyaml": "yaml",
                   "beautifulsoup4": "bs4"}

#: legacy global-state numpy.random functions (np.random.seed & friends)
#: — process-wide RNG state breaks reproducibility under vmapped
#: populations and forked sandbox children; np.random.default_rng /
#: Generator thread state explicitly and stay allowed
_LEGACY_NP_RANDOM = {
    "seed", "rand", "randn", "randint", "random", "random_sample",
    "ranf", "sample", "uniform", "normal", "standard_normal", "choice",
    "permutation", "shuffle", "beta", "binomial", "poisson",
    "exponential", "gamma", "laplace", "lognormal", "multinomial"}

#: call names that trace their function argument(s)
_TRACING_CALLS = {"jit", "vmap", "pmap", "scan", "while_loop", "cond",
                  "fori_loop", "checkpoint", "remat"}

#: host-sync coercions that force a traced value to the host
_HOST_SYNC_NAMES = {"float", "int", "bool"}


def verify_template_source(
        source: str,
        class_name: Optional[str] = None,
        declared_dependencies: Optional[Dict[str, Optional[str]]] = None,
        filename: str = "<uploaded>",
) -> VerificationReport:
    """Run the full pass pipeline; never raises on bad input — every
    problem becomes a finding so callers get ONE shape to handle."""
    report = VerificationReport(class_name=class_name)
    try:
        tree = astutil.parse(source, filename)
    except SyntaxError as e:
        report.add("TPL005", f"template does not parse: {e.msg}",
                   ERROR, filename, int(e.lineno or 0), int(e.offset or 0))
        return report

    classes = astutil.class_map(tree)
    target = _resolve_target_class(report, classes, class_name, filename)
    _check_imports(report, tree, classes, target, declared_dependencies,
                   filename)
    if target is None:
        return report

    methods = astutil.own_and_inherited_methods(target, classes)
    knob_names = _check_structure(report, tree, target, classes, methods,
                                  filename)
    spec = _check_population(report, target, classes, methods, knob_names,
                             filename)
    gen_spec = _check_generation(report, target, classes, methods, filename)
    _check_jax_pitfalls(report, tree, filename)
    report.capabilities = {
        "population": spec is not None,
        "population_spec": spec,
        "generation": gen_spec is not None,
        "generation_spec": gen_spec,
    }
    return report


def verify_template_bytes(
        model_file_bytes: bytes,
        class_name: Optional[str] = None,
        declared_dependencies: Optional[Dict[str, Optional[str]]] = None,
        filename: str = "<uploaded>",
) -> VerificationReport:
    """Byte-level entry point for the upload path (Admin.create_model)."""
    try:
        source = model_file_bytes.decode("utf-8")
    except UnicodeDecodeError as e:
        report = VerificationReport(class_name=class_name)
        report.add("TPL005", f"template is not UTF-8 text: {e}", ERROR,
                   filename)
        return report
    return verify_template_source(source, class_name,
                                  declared_dependencies, filename)


def static_population_capability(
        source, class_name: Optional[str] = None) -> Optional[Dict[str, Any]]:
    """The static mirror of sdk/model.population_capability: the parsed
    PopulationSpec dict iff the template declares one AND overrides all
    three population methods — else None. THE capability oracle for
    callers that must not execute uploaded code (doctor.py); replaces
    the old ``b"population_spec" in bytes`` sniff."""
    if isinstance(source, bytes):
        report = verify_template_bytes(source, class_name)
    else:
        report = verify_template_source(source, class_name)
    if report.capabilities.get("population"):
        return report.capabilities.get("population_spec")
    return None


def static_generation_capability(
        source, class_name: Optional[str] = None) -> Optional[Dict[str, Any]]:
    """The static mirror of sdk/model.generation_capability: the parsed
    GenerationSpec dict iff the template declares one AND overrides the
    three decode methods — else None. THE capability oracle for callers
    that must not execute uploaded code (Admin.create_train_job's
    task/capability consistency check, doctor.py)."""
    if isinstance(source, bytes):
        report = verify_template_bytes(source, class_name)
    else:
        report = verify_template_source(source, class_name)
    if report.capabilities.get("generation"):
        return report.capabilities.get("generation_spec")
    return None


# -- pass: class resolution -------------------------------------------------

def _resolve_target_class(
        report: VerificationReport, classes: Dict[str, ast.ClassDef],
        class_name: Optional[str], filename: str,
) -> Optional[ast.ClassDef]:
    if class_name is not None:
        cls = classes.get(class_name)
        if cls is None:
            report.add("TPL004",
                       f"class {class_name!r} not found in template", ERROR,
                       filename)
            return None
        if not astutil.is_model_subclass(cls, classes):
            report.add("TPL004",
                       f"class {class_name!r} does not subclass BaseModel",
                       ERROR, filename, cls.lineno)
            return None
        return cls
    candidates = [c for c in classes.values()
                  if astutil.is_model_subclass(c, classes)]
    if not candidates:
        report.add("TPL004", "no BaseModel subclass found in template",
                   ERROR, filename)
        return None
    # last definition wins, matching what an import-and-getattr would see
    cls = candidates[-1]
    report.class_name = cls.name
    return cls


# -- pass: imports vs declared dependencies + sandbox policy ----------------

def _check_imports(
        report: VerificationReport, tree: ast.Module,
        classes: Dict[str, ast.ClassDef], target: Optional[ast.ClassDef],
        declared_dependencies: Optional[Dict[str, Optional[str]]],
        filename: str) -> None:
    imports = astutil.imported_top_modules(tree)
    # the sandbox-policy pass sees EVERY import, even ones a hostile
    # template hides behind try/except or a __main__ guard
    all_imports = astutil.imported_top_modules(tree, include_guarded=True)
    declared: Set[str] = set()
    deps = declared_dependencies
    if deps is None and target is not None:
        node = astutil.class_attr_assign(target, classes, "dependencies")
        if node is not None:
            if astutil.is_constant(node):
                try:
                    deps = astutil.literal_value(node)
                except ValueError:
                    # unevaluable corner (unhashable key, div-zero):
                    # same contract as a non-literal dict
                    deps = None
                if deps is not None and not isinstance(deps, dict):
                    report.add("TPL007",
                               "dependencies attribute must be a dict of "
                               f"{{package: version}}, got "
                               f"{type(deps).__name__}", WARN, filename,
                               node.lineno)
                    deps = None
            else:
                report.add("TPL007",
                           "dependencies attribute is not a literal dict — "
                           "the platform cannot provision what it cannot "
                           "read statically", WARN, filename, node.lineno)
    for name in (deps or {}):
        lowered = str(name).lower()
        declared.add(_DIST_TO_IMPORT.get(lowered, lowered.replace("-", "_")))
        declared.add(str(name))
    for mod, lineno in sorted(all_imports.items(), key=lambda kv: kv[1]):
        if mod in FORBIDDEN_IMPORTS:
            report.add("SBX001",
                       f"import of {mod!r} is forbidden in the trial "
                       "sandbox — a template must not spawn processes or "
                       "load native code (docs/static-analysis.md)", ERROR,
                       filename, lineno)
    for mod, lineno in sorted(imports.items(), key=lambda kv: kv[1]):
        if mod in FORBIDDEN_IMPORTS or mod in IMPLICIT_MODULES \
                or mod in declared:
            continue
        report.add("TPL003",
                   f"import {mod!r} is neither a platform-provided module "
                   "nor declared in this template's dependencies — the "
                   "trial would die at import time on a fresh worker",
                   ERROR, filename, lineno)


# -- pass: structural contract ----------------------------------------------

def _check_structure(
        report: VerificationReport, tree: ast.Module,
        target: ast.ClassDef, classes: Dict[str, ast.ClassDef],
        methods: Dict[str, ast.FunctionDef], filename: str,
) -> Optional[Set[str]]:
    for name in REQUIRED_METHODS:
        if name not in methods:
            report.add("TPL001",
                       f"{target.name} is missing required method "
                       f"{name}() — the BaseModel contract "
                       "(docs/model-templates.md)", ERROR, filename,
                       target.lineno)
    gkc = methods.get("get_knob_config")
    if gkc is None:
        return None
    decorators = {astutil.terminal_name(d) for d in gkc.decorator_list}
    if "staticmethod" not in decorators and "classmethod" not in decorators:
        args = [a.arg for a in gkc.args.args]
        if args[:1] == ["self"]:
            report.add("TPL006",
                       "get_knob_config must be a @staticmethod — the "
                       "advisor reads the knob space from the CLASS, "
                       "before any instance exists", ERROR, filename,
                       gkc.lineno)
    return _KnobConfigEval(report, tree, classes, filename).run(gkc)


class _KnobSpace:
    """Abstract value for a knob-config dict under construction."""

    def __init__(self, names=()):
        self.names: Set[str] = set(names)


class _KnobConfigEval:
    """A tiny straight-line interpreter over ``get_knob_config`` bodies.

    Proves the knob space is *literally evaluable* without running user
    code, while accepting the idioms real templates use:

    - ``return {"lr": FloatKnob(1e-4, 1e-1)}`` — dict literal of knob
      constructors with literal args (module-level constants resolve);
    - ``cfg = dict(Parent.get_knob_config()); cfg["epochs"] =
      FixedKnob(1); return cfg`` — subclass inherits a same-file
      parent's (itself evaluable) config and pins entries.

    Anything else — a computed key, a constructor fed runtime state, a
    helper call the analyzer cannot see through — is TPL002: the
    advisor would have to *execute* the template to learn the space.
    """

    _MAX_DEPTH = 4

    def __init__(self, report: Optional[VerificationReport],
                 tree: ast.Module, classes: Dict[str, ast.ClassDef],
                 filename: str, _depth: int = 0,
                 _seen: Optional[Set[str]] = None):
        self.report = report
        self.tree = tree
        self.classes = classes
        self.filename = filename
        self.depth = _depth
        self.seen = _seen if _seen is not None else set()
        self.module_env = self._module_constants(tree)

    @staticmethod
    def _module_constants(tree: ast.Module) -> Dict[str, ast.AST]:
        # ``_DIM = 16`` / ``_DIM, _CLASSES = 8, 3`` at module level are
        # part of the literal vocabulary — templates hoist shared
        # dimensions there
        env: Dict[str, ast.AST] = {}
        for n in tree.body:
            if not isinstance(n, ast.Assign):
                continue
            for t in n.targets:
                if isinstance(t, ast.Name) and astutil.is_constant(n.value):
                    env[t.id] = n.value
                elif isinstance(t, ast.Tuple) \
                        and isinstance(n.value, ast.Tuple) \
                        and len(t.elts) == len(n.value.elts):
                    for te, ve in zip(t.elts, n.value.elts):
                        if isinstance(te, ast.Name) \
                                and astutil.is_constant(ve):
                            env[te.id] = ve
        return env

    def _fail(self, message: str, node: ast.AST) -> None:
        if self.report is not None:
            self.report.add("TPL002", message, ERROR, self.filename,
                            getattr(node, "lineno", 0))

    def run(self, gkc: ast.FunctionDef) -> Optional[Set[str]]:
        env: Dict[str, Any] = dict(self.module_env)
        spaces: List[Optional[_KnobSpace]] = []
        self._interp(gkc.body, env, spaces)
        if not spaces:
            self._fail("get_knob_config never returns a knob config", gkc)
            return None
        if any(s is None for s in spaces):
            return None
        names: Set[str] = set()
        for s in spaces:
            names |= s.names
        return names

    def _interp(self, stmts: List[ast.stmt], env: Dict[str, Any],
                spaces: List[Optional[_KnobSpace]]) -> None:
        for stmt in stmts:
            if isinstance(stmt, ast.Return):
                before = len(self.report.findings) if self.report else 0
                spaces.append(self._eval(stmt.value, env)
                              if stmt.value is not None else None)
                if spaces[-1] is None and stmt.value is not None \
                        and (self.report is None
                             or len(self.report.findings) == before):
                    # no specific finding fired — say why the whole
                    # return is opaque
                    self._fail(
                        "get_knob_config must return a statically "
                        "evaluable dict of knob constructors "
                        f"(cannot evaluate "
                        f"{ast.unparse(stmt.value)[:60]}) — the advisor "
                        "derives the search space without running user "
                        "code", stmt)
            elif isinstance(stmt, ast.Assign) and len(stmt.targets) == 1 \
                    and isinstance(stmt.targets[0], ast.Name):
                value = self._eval(stmt.value, env, quiet=True)
                if value is not None:
                    env[stmt.targets[0].id] = value
                elif astutil.is_constant(stmt.value):
                    env[stmt.targets[0].id] = stmt.value
                else:
                    env.pop(stmt.targets[0].id, None)  # opaque now
            elif isinstance(stmt, ast.Assign) and len(stmt.targets) == 1 \
                    and isinstance(stmt.targets[0], ast.Subscript):
                self._setitem(stmt.targets[0], stmt.value, env)
            elif isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef,
                                   ast.ClassDef)):
                continue
            elif isinstance(stmt, (ast.If, ast.For, ast.While, ast.With,
                                   ast.Try)):
                # branches are interpreted against the shared env
                # (last-wins approximation — per-entry evaluability is
                # still proven on every path that assigns)
                for body in ([stmt.body] + [getattr(stmt, "orelse", [])]
                             + [getattr(stmt, "finalbody", [])]
                             + [h.body for h in getattr(
                                 stmt, "handlers", []) or []]):
                    if body:
                        self._interp(body, env, spaces)

    def _setitem(self, target: ast.Subscript, value: ast.AST,
                 env: Dict[str, Any]) -> None:
        base, key = target.value, target.slice
        if not (isinstance(base, ast.Name)
                and isinstance(env.get(base.id), _KnobSpace)):
            return
        if not (isinstance(key, ast.Constant) and isinstance(key.value,
                                                             str)):
            self._fail("knob config keys must be string literals", target)
            env.pop(base.id, None)
            return
        bad = _non_literal_knob_expr(value, env)
        if bad is not None:
            self._fail(
                f"knob {key.value!r} is not statically evaluable "
                f"({ast.unparse(bad)[:80]}) — knob constructors must "
                "take literal arguments", value)
            env.pop(base.id, None)
            return
        env[base.id].names.add(key.value)

    def _eval(self, expr: ast.AST, env: Dict[str, Any],
              quiet: bool = False) -> Optional[_KnobSpace]:
        if isinstance(expr, ast.Name):
            value = env.get(expr.id)
            return value if isinstance(value, _KnobSpace) else None
        if isinstance(expr, ast.Dict):
            return self._eval_dict_literal(expr, env, quiet)
        if isinstance(expr, ast.Call):
            fname = astutil.terminal_name(expr.func)
            if fname == "dict":
                if not expr.args and not expr.keywords:
                    return _KnobSpace()
                if len(expr.args) == 1 and not expr.keywords:
                    return self._eval(expr.args[0], env, quiet)
                return None
            if fname == "get_knob_config" \
                    and isinstance(expr.func, ast.Attribute) \
                    and isinstance(expr.func.value, ast.Name):
                return self._eval_parent_config(expr.func.value.id)
        return None

    def _eval_dict_literal(self, expr: ast.Dict, env: Dict[str, Any],
                           quiet: bool) -> Optional[_KnobSpace]:
        space = _KnobSpace()
        ok = True
        for key, value in zip(expr.keys, expr.values):
            if not (isinstance(key, ast.Constant)
                    and isinstance(key.value, str)):
                if not quiet:
                    self._fail("knob config keys must be string literals",
                               key if key is not None else expr)
                ok = False
                continue
            space.names.add(key.value)
            bad = _non_literal_knob_expr(value, env)
            if bad is not None:
                if not quiet:
                    self._fail(
                        f"knob {key.value!r} is not statically "
                        f"evaluable ({ast.unparse(bad)[:80]}) — knob "
                        "constructors must take literal arguments", value)
                ok = False
        return space if ok else None

    def _eval_parent_config(self, class_name: str) -> Optional[_KnobSpace]:
        """``Parent.get_knob_config()`` where Parent is defined in the
        SAME file: recursively prove the parent's config evaluable and
        inherit its knob names."""
        cls = self.classes.get(class_name)
        if cls is None or self.depth >= self._MAX_DEPTH \
                or class_name in self.seen:
            return None
        parent_gkc = astutil.own_and_inherited_methods(
            cls, self.classes).get("get_knob_config")
        if parent_gkc is None:
            return None
        sub = _KnobConfigEval(None, self.tree, self.classes, self.filename,
                              _depth=self.depth + 1,
                              _seen=self.seen | {class_name})
        names = sub.run(parent_gkc)
        return _KnobSpace(names) if names is not None else None


def _non_literal_knob_expr(node: ast.AST, env: Dict[str, ast.AST],
                           depth: int = 0) -> Optional[ast.AST]:
    """None when ``node`` is an evaluable knob expression, else the
    offending sub-node."""
    if depth > 4:
        return node
    if isinstance(node, ast.Name) and isinstance(env.get(node.id), ast.AST):
        return _non_literal_knob_expr(env[node.id], env, depth + 1)
    if astutil.is_constant(node):
        return None
    if isinstance(node, ast.Call):
        name = astutil.terminal_name(node.func)
        if name is None or not (name in KNOWN_KNOB_CLASSES
                                or name.endswith("Knob")):
            return node
        for arg in list(node.args) + [kw.value for kw in node.keywords]:
            if isinstance(arg, ast.Name) \
                    and isinstance(env.get(arg.id), ast.AST):
                arg = env[arg.id]
            if not astutil.is_constant(arg):
                return arg
        return None
    return node


# -- pass: PopulationSpec consistency ---------------------------------------

def _check_population(
        report: VerificationReport, target: ast.ClassDef,
        classes: Dict[str, ast.ClassDef],
        methods: Dict[str, ast.FunctionDef],
        knob_names: Optional[Set[str]], filename: str,
) -> Optional[Dict[str, Any]]:
    node = astutil.class_attr_assign(target, classes, "population_spec")
    if node is None:
        return None
    if isinstance(node, ast.Constant) and node.value is None:
        return None
    lineno = getattr(node, "lineno", target.lineno)
    if not (isinstance(node, ast.Call)
            and astutil.terminal_name(node.func) == "PopulationSpec"):
        report.add("POP004",
                   "population_spec is not a literal PopulationSpec(...) "
                   "call — capability cannot be verified statically and "
                   "the worker may silently run scalar", WARN, filename,
                   lineno)
        return None
    kwargs = {kw.arg: kw.value for kw in node.keywords if kw.arg}
    dyn_node = node.args[0] if node.args else kwargs.get("dynamic_knobs")
    dynamic: Optional[Tuple[str, ...]] = None
    if dyn_node is not None and astutil.is_constant(dyn_node):
        try:
            value = astutil.literal_value(dyn_node)
        except ValueError:
            value = None
        if isinstance(value, (list, tuple)) and all(
                isinstance(v, str) for v in value):
            dynamic = tuple(value)
    if dynamic is None:
        report.add("POP004",
                   "PopulationSpec dynamic_knobs is not a literal "
                   "list/tuple of knob names", WARN, filename, lineno)
        return None
    max_members = 8
    mm_node = (node.args[1] if len(node.args) > 1
               else kwargs.get("max_members"))
    if mm_node is not None and astutil.is_constant(mm_node):
        try:
            max_members = int(astutil.literal_value(mm_node))
        except (TypeError, ValueError):
            pass
    missing = [m for m in POPULATION_METHODS if m not in methods]
    if missing:
        report.add("POP002",
                   f"{target.name} declares population_spec but does not "
                   f"override {', '.join(m + '()' for m in missing)} — "
                   "the worker would silently fall back to scalar trials "
                   "(sdk/model.population_capability)", ERROR, filename,
                   lineno)
        return None
    if knob_names is not None:
        rogue = [k for k in dynamic if k not in knob_names]
        if rogue:
            report.add("POP001",
                       f"dynamic knob(s) {rogue} are not in the knob "
                       "config — the vmap partitioner "
                       "(worker/vmap_partition.py) cannot bucket on a "
                       "knob the advisor never proposes", ERROR, filename,
                       lineno)
            return None
    for mname in ("train", "train_population"):
        fn = methods.get(mname)
        if fn is not None:
            _check_dynamic_knob_branching(report, fn, set(dynamic), filename)
    return {"dynamic_knobs": list(dynamic), "max_members": max_members}


# -- pass: generative capability contract (GEN00x) ---------------------------

#: decode methods a generation-capable template must override, with the
#: positional-arg count (self included) the worker calls them with —
#: sdk/model.py BaseModel.{init_kv_cache,prefill,decode_step}
GENERATION_SIGNATURES = {
    "init_kv_cache": 2,   # (self, max_slots)
    "prefill": 4,         # (self, cache, slot, prompt_ids)
    "decode_step": 4,     # (self, cache, ids, positions)
}

#: OPTIONAL paged-allocator refinement (sdk/model.py
#: GENERATION_PAGED_METHODS): arity-checked only when the template
#: overrides them — absence just means the worker serves the legacy ring
PAGED_GENERATION_SIGNATURES = {
    "init_paged_kv_cache": 3,  # (self, pool_blocks, block_tokens)
    "paged_prefill": 5,        # (self, cache, block_table, ids, start)
    "paged_decode_step": 5,    # (self, cache, ids, positions, tables)
    "kv_copy_blocks": 4,       # (self, cache, src, dst)
}

#: OPTIONAL sampling + speculative-decoding refinement (sdk/model.py
#: GENERATION_SAMPLING_METHODS / GENERATION_SPEC_METHODS): arity-checked
#: only when overridden — absence means greedy-only / plain-decode serving
SAMPLING_GENERATION_SIGNATURES = {
    "decode_step_sampled": 5,        # (self, cache, ids, positions,
                                     #  sampling)
    "decode_steps_sampled": 6,       # (self, cache, ids, positions, k,
                                     #  sampling) — optional fused
                                     #  draft-proposal burst
    "paged_decode_step_sampled": 6,  # (self, cache, ids, positions,
                                     #  tables, sampling)
    "paged_verify_step": 7,          # (self, cache, ids, positions,
                                     #  tables, draft_probs, sampling)
}


def _check_generation(
        report: VerificationReport, target: ast.ClassDef,
        classes: Dict[str, ast.ClassDef],
        methods: Dict[str, ast.FunctionDef],
        filename: str,
) -> Optional[Dict[str, Any]]:
    """The generative capability contract (mirrors _check_population):
    a template advertising ``generation_spec`` must override the three
    decode methods with the signatures the slot scheduler
    (worker/generation.py) calls. Half-wired = WARN — the capability is
    simply not advertised (generation_capability returns None), and the
    task/capability consistency check at upload turns that into a typed
    400 for TEXT_GENERATION uploads."""
    node = astutil.class_attr_assign(target, classes, "generation_spec")
    if node is None:
        return None
    if isinstance(node, ast.Constant) and node.value is None:
        return None
    lineno = getattr(node, "lineno", target.lineno)
    if not (isinstance(node, ast.Call)
            and astutil.terminal_name(node.func) == "GenerationSpec"):
        report.add("GEN003",
                   "generation_spec is not a literal GenerationSpec(...) "
                   "call — capability cannot be verified statically and a "
                   "TEXT_GENERATION upload would be refused", WARN,
                   filename, lineno)
        return None
    kwargs = {kw.arg: kw.value for kw in node.keywords if kw.arg}
    args = list(node.args)
    spec: Dict[str, Any] = {"eos_token_id": None, "max_context": 128}
    for key, pos in (("eos_token_id", 0), ("max_context", 1)):
        val_node = args[pos] if len(args) > pos else kwargs.get(key)
        if val_node is not None and astutil.is_constant(val_node):
            try:
                spec[key] = astutil.literal_value(val_node)
            except ValueError:
                pass
    missing = [m for m in GENERATION_SIGNATURES if m not in methods]
    if missing:
        report.add("GEN001",
                   f"{target.name} declares generation_spec but does not "
                   f"override {', '.join(m + '()' for m in missing)} — the "
                   "template is NOT generation-capable "
                   "(sdk/model.generation_capability) and cannot be "
                   "uploaded under task TEXT_GENERATION", WARN, filename,
                   lineno)
        return None
    to_check = dict(GENERATION_SIGNATURES)
    # the paged/sampling refinements are opt-in: only overridden methods
    # are checked
    to_check.update({m: n for m, n in PAGED_GENERATION_SIGNATURES.items()
                     if m in methods})
    to_check.update({m: n
                     for m, n in SAMPLING_GENERATION_SIGNATURES.items()
                     if m in methods})
    for mname, n_args in to_check.items():
        fn = methods[mname]
        if fn.args.vararg is not None:
            continue  # *args swallows anything the worker passes
        # callable with exactly n_args positionals: defaults shrink the
        # required count, positional-only params count like ordinary ones
        total = len(fn.args.posonlyargs) + len(fn.args.args)
        required = total - len(fn.args.defaults)
        if not required <= n_args <= total:
            report.add("GEN002",
                       f"{mname}() accepts {required}..{total} positional "
                       f"arg(s) but the slot scheduler calls it with "
                       f"{n_args} (worker/generation.py) — the first "
                       "mid-serving call would raise TypeError", WARN,
                       filename, fn.lineno)
    return spec


def _check_dynamic_knob_branching(
        report: VerificationReport, fn: ast.FunctionDef,
        dynamic: Set[str], filename: str) -> None:
    """Members of one vmapped program share ONE compiled step — a Python
    ``if``/``while`` on a knob that differs across members would give
    each member a different trace. Flags branch tests that reference a
    dynamic-knob subscript (``knobs["lr"]``/``k.get("lr")``) or a name
    assigned from one (single-level taint, deliberately not transitive:
    deeper flows are where heuristics start lying)."""

    def knob_ref(n: ast.AST) -> bool:
        if isinstance(n, ast.Subscript):
            s = n.slice
            return isinstance(s, ast.Constant) and s.value in dynamic
        if isinstance(n, ast.Call) and isinstance(n.func, ast.Attribute) \
                and n.func.attr == "get" and n.args:
            a = n.args[0]
            return isinstance(a, ast.Constant) and a.value in dynamic
        return False

    tainted: Set[str] = set()
    for node in astutil.walk_no_nested_functions(fn):
        if isinstance(node, ast.Assign) and astutil.contains(
                node.value, knob_ref):
            for t in node.targets:
                if isinstance(t, ast.Name):
                    tainted.add(t.id)

    def test_hits(n: ast.AST) -> bool:
        return knob_ref(n) or (isinstance(n, ast.Name)
                               and isinstance(n.ctx, ast.Load)
                               and n.id in tainted)

    for node in astutil.walk_no_nested_functions(fn):
        test = None
        if isinstance(node, (ast.If, ast.While)):
            test = node.test
        elif isinstance(node, ast.IfExp):
            test = node.test
        if test is not None and astutil.contains(test, test_hits):
            report.add("POP003",
                       f"{fn.name}() branches on a dynamic knob — members "
                       "of one vmapped program must share one trace; "
                       "branch on program-shaping knobs only, or use "
                       "jnp.where/lax.cond on traced values", ERROR,
                       filename, node.lineno)


# -- pass: JAX tracing pitfalls ---------------------------------------------

def _traced_functions(tree: ast.Module) -> List[ast.AST]:
    """Function bodies that run under jax tracing: decorated with
    jit/vmap/pmap (directly or through partial), or passed by name or as
    a lambda to a tracing call (jax.jit(f), jax.lax.scan(step, ...))."""
    named: Dict[str, ast.AST] = {}
    for node in ast.walk(tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            named[node.name] = node
    traced: List[ast.AST] = []
    seen: Set[int] = set()

    def mark(fn: Optional[ast.AST]) -> None:
        if fn is not None and id(fn) not in seen:
            seen.add(id(fn))
            traced.append(fn)

    for node in ast.walk(tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            for dec in node.decorator_list:
                name = astutil.terminal_name(
                    dec.func if isinstance(dec, ast.Call) else dec)
                if name in ("jit", "vmap", "pmap"):
                    mark(node)
                elif isinstance(dec, ast.Call) and name == "partial":
                    if any(astutil.terminal_name(a) in ("jit", "vmap",
                                                        "pmap")
                           for a in dec.args):
                        mark(node)
        elif isinstance(node, ast.Call):
            name = astutil.terminal_name(node.func)
            if name in _TRACING_CALLS:
                for arg in node.args:
                    if isinstance(arg, ast.Lambda):
                        mark(arg)
                    elif isinstance(arg, ast.Name) and arg.id in named:
                        mark(named[arg.id])
    return traced


def _references_static_shape(node: ast.AST) -> bool:
    """``int(x.shape[0])``-style coercions are FINE under jit — shapes
    (and dtypes/ndim) are static at trace time; only *values* are
    traced."""
    return astutil.contains(
        node, lambda n: isinstance(n, ast.Attribute)
        and n.attr in ("shape", "ndim", "dtype", "size")) is not None


def _check_jax_pitfalls(report: VerificationReport, tree: ast.Module,
                        filename: str) -> None:
    # tracing reachability is approximate (no call graph), so every
    # JAX-pitfall detector reports WARN — findings.py's invariant:
    # a heuristic must never be able to lock a working template out of
    # the platform at enforce; structural/population/sandbox passes are
    # the error-class ones
    for fn in _traced_functions(tree):
        body = fn.body if isinstance(fn, ast.Lambda) else fn
        nodes = ast.walk(body) if isinstance(fn, ast.Lambda) \
            else astutil.walk_no_nested_functions(fn)
        for node in nodes:
            if isinstance(node, ast.Call):
                tname = astutil.terminal_name(node.func)
                root = astutil.root_name(node.func)
                if isinstance(node.func, ast.Attribute) \
                        and node.func.attr == "item" and not node.args:
                    report.add(
                        "JAX001",
                        ".item() inside a jit/vmap-traced function forces "
                        "a device sync per call (or a tracer error) — "
                        "return the array and coerce outside the traced "
                        "region", WARN, filename, node.lineno)
                elif isinstance(node.func, ast.Name) \
                        and tname in _HOST_SYNC_NAMES and node.args \
                        and not astutil.is_constant(node.args[0]) \
                        and not _references_static_shape(node.args[0]):
                    report.add(
                        "JAX001",
                        f"{tname}() on a traced value inside a jit/vmap-"
                        "traced function raises ConcretizationTypeError "
                        "at trial time — keep values as arrays under "
                        "trace", WARN, filename, node.lineno)
                elif root in ("np", "numpy", "onp") \
                        and tname in ("asarray", "array") \
                        and not (node.args
                                 and astutil.is_constant(node.args[0])):
                    # np.array([0.5, 2.0]) of constants is just a
                    # closed-over literal — only flag host pulls of
                    # non-constant (potentially traced) values
                    report.add(
                        "JAX001",
                        f"{astutil.dotted_name(node.func)}() inside a "
                        "traced function pulls the value to host memory "
                        "— use jnp inside traced code", WARN, filename,
                        node.lineno)
            elif isinstance(node, (ast.Assign, ast.AugAssign)):
                targets = (node.targets if isinstance(node, ast.Assign)
                           else [node.target])
                for t in targets:
                    if isinstance(t, ast.Attribute) and isinstance(
                            t.value, ast.Name) and t.value.id == "self":
                        report.add(
                            "JAX003",
                            f"assignment to self.{t.attr} inside a "
                            "jit/vmap-traced function — the side effect "
                            "runs once at trace time, then never again "
                            "(and leaks tracers into instance state)",
                            WARN, filename, node.lineno)
    # legacy global RNG: anywhere in the template (trial workers share a
    # process with platform code, and vmapped members share the process)
    for node in ast.walk(tree):
        if isinstance(node, ast.Call):
            dotted = astutil.dotted_name(node.func) or ""
            parts = dotted.split(".")
            if len(parts) >= 3 and parts[0] in ("np", "numpy") \
                    and parts[1] == "random" \
                    and parts[-1] in _LEGACY_NP_RANDOM:
                report.add(
                    "JAX002",
                    f"{dotted}() uses process-global RNG state — thread "
                    "an explicit np.random.default_rng(seed) / jax PRNG "
                    "key instead (vmapped members and forked sandbox "
                    "children share that state)", WARN, filename,
                    node.lineno)
    _check_recompile_risk(report, tree, filename)


#: methods whose bodies run once PER SERVED REQUEST — a jit() there with
#: static_argnums fed from the request recompiles on every novel value
_PER_REQUEST_METHODS = {"predict", "predict_batch", "generate"}


def _check_recompile_risk(report: VerificationReport, tree: ast.Module,
                          filename: str) -> None:
    """JAX004 — the static half of the recompile-cost work: shapes that
    force XLA to compile a fresh program per loop iteration or per
    request instead of once.

    (a) ``jax.jit``/``vmap`` applied inside a loop body to a closure
    that captures a loop-varying Python value: every iteration traces a
    new function identity with a new constant baked in. Loop variables
    derived from ``x.shape``/``ndim``/``dtype``/``size`` are exempt
    (the JAX001 carve-out carried over: shape-bucketed recompiles are a
    deliberate, bounded cost), as are constant rebinds.

    (b) ``jit(..., static_argnums=/static_argnames=)`` inside a
    per-request method: a static argument fed from request values
    recompiles per novel value, the unbounded-compile-cache shape.
    Both WARN — reachability is approximate, like every JAX detector."""
    named_funcs: Dict[str, ast.AST] = {
        n.name: n for n in ast.walk(tree)
        if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))}
    for loop in ast.walk(tree):
        if not isinstance(loop, (ast.For, ast.While)):
            continue
        loop_varying: Set[str] = set()
        exempt: Set[str] = set()
        if isinstance(loop, ast.For):
            for t in ast.walk(loop.target):
                if isinstance(t, ast.Name):
                    loop_varying.add(t.id)
        for n in ast.walk(loop):
            if isinstance(n, ast.Assign):
                names = [t.id for t in n.targets
                         if isinstance(t, ast.Name)]
                if _references_static_shape(n.value) \
                        or astutil.is_constant(n.value):
                    exempt.update(names)
                else:
                    loop_varying.update(names)
            elif isinstance(n, ast.AugAssign) \
                    and isinstance(n.target, ast.Name):
                loop_varying.add(n.target.id)
        loop_varying -= exempt
        if not loop_varying:
            continue
        for n in ast.walk(loop):
            if not isinstance(n, ast.Call):
                continue
            if astutil.terminal_name(n.func) not in ("jit", "vmap",
                                                     "pmap"):
                continue
            callee = n.args[0] if n.args else None
            if isinstance(callee, ast.Lambda):
                params = {a.arg for a in callee.args.args}
                body: ast.AST = callee.body
            elif isinstance(callee, ast.Name) \
                    and callee.id in named_funcs:
                fdef = named_funcs[callee.id]
                params = {a.arg for a in fdef.args.args}
                body = fdef
            else:
                continue
            captured = sorted(
                node.id for node in ast.walk(body)
                if isinstance(node, ast.Name)
                and isinstance(node.ctx, ast.Load)
                and node.id in loop_varying and node.id not in params)
            if captured:
                report.add(
                    "JAX004",
                    f"jit/vmap inside a loop closes over loop-varying "
                    f"{', '.join(captured)!s} — every iteration traces "
                    "and compiles a fresh program with the value baked "
                    "in; hoist the jit out of the loop and pass the "
                    "value as a traced argument", WARN, filename,
                    n.lineno)
    # (b) static_argnums on the per-request path
    for fn in ast.walk(tree):
        if not isinstance(fn, (ast.FunctionDef, ast.AsyncFunctionDef)) \
                or fn.name not in _PER_REQUEST_METHODS:
            continue
        for n in ast.walk(fn):
            if isinstance(n, ast.Call) \
                    and astutil.terminal_name(n.func) == "jit" \
                    and any(kw.arg in ("static_argnums", "static_argnames")
                            for kw in n.keywords):
                report.add(
                    "JAX004",
                    f"jit(static_argnums=...) inside {fn.name}() marks "
                    "request-fed values static — every novel value "
                    "compiles another program and the compile cache "
                    "grows without bound; jit once at load time and "
                    "trace the value instead", WARN, filename, n.lineno)
