"""Static-analysis subsystem: three AST-based heads, zero
untrusted-code execution (docs/static-analysis.md).

Head 1 — **template verifier** (:mod:`.template`): a pass pipeline over
uploaded model source, wired into ``Admin.create_model`` behind
``RAFIKI_VERIFY_TEMPLATES=enforce|warn|off``, exposed as a dry run via
``POST /models/verify`` / ``Client.verify_model``, and runnable locally
as ``python -m rafiki_tpu.analysis template.py [ClassName]``.

Head 2 — **framework self-lint** (:mod:`.framework`): the env-knob /
broad-except / lock / HTTP-door disciplines PRs 1–8 established by
convention, enforced over the whole package as a tier-1 test
(tests/test_framework_lint.py).

Head 3 — **concurrency analyzer** (:mod:`.concurrency`): whole-package
lockset inference, lock-order deadlock detection, and atomicity lint
with no annotations required — rides ``lint_package()`` (so tier-1 and
``--self-lint`` enforce it) and doctor's *concurrency lint* check.
"""

from rafiki_tpu.analysis.findings import (
    CODES,
    ERROR,
    WARN,
    Finding,
    ModelVerificationError,
    VerificationReport,
)
from rafiki_tpu.analysis.framework import lint_package
from rafiki_tpu.analysis.template import (
    static_generation_capability,
    static_population_capability,
    verify_template_bytes,
    verify_template_source,
)

__all__ = [
    "CODES",
    "ERROR",
    "WARN",
    "Finding",
    "ModelVerificationError",
    "VerificationReport",
    "lint_package",
    "static_generation_capability",
    "static_population_capability",
    "verify_template_bytes",
    "verify_template_source",
    "verify_mode",
]


def verify_mode() -> str:
    """The active upload-verification mode: ``enforce`` (error findings
    reject the upload with a typed ModelVerificationError), ``warn``
    (findings are logged + persisted on the model row, upload proceeds),
    or ``off`` (analysis skipped entirely; doctor WARNs when live jobs
    exist). Unrecognized values fall back to ``enforce`` — a typo'd
    knob must not silently disable the safety net."""
    import os

    mode = os.environ.get("RAFIKI_VERIFY_TEMPLATES", "enforce").lower()
    return mode if mode in ("enforce", "warn", "off") else "enforce"
