"""Head 2 — the framework self-lint: invariants PRs 1–8 established by
convention, now enforced as a tier-1 test (tests/test_framework_lint.py)
over the whole ``rafiki_tpu`` package.

Disciplines (annotation grammar in docs/static-analysis.md):

- **env knobs** (FWK101-103): every constant ``RAFIKI_*`` name read via
  ``os.environ`` / ``os.getenv`` must be declared in config.py (the
  declaration point is config.py's own source — the ``ENV_KNOBS`` /
  ``ENV_INTERNAL`` catalogs plus any knob config.py itself reads), and
  operator-facing knobs must additionally appear in scripts/env.sh and
  somewhere under docs/. ``ENV_INTERNAL`` names are platform plumbing
  (worker bootstrap ids etc.) exempt from the operator catalogs.

- **broad excepts** (FWK201): an ``except Exception`` (or bare
  ``except``) handler must re-raise, log, or carry an explicit
  ``# lint: absorb(reason)`` annotation on the ``except`` line (or the
  line above) — silent absorption is allowed only where absorption IS
  the contract, and then it must say so.

- **locks** (FWK301/302): opt-in. A ``self.attr = ...`` assignment
  annotated ``# guarded-by: _lock`` makes every other access of
  ``self.attr`` in that class require a lexically-enclosing
  ``with self._lock:`` — or the accessing method itself carries
  ``# guarded-by: _lock`` on its ``def`` line (contract: callers hold
  the lock), or the access line carries ``# lint: unguarded(reason)``.

- **HTTP doors** (FWK401/402): in the three door modules, an except
  clause naming a typed ``*Error`` must answer with an explicit 4xx/5xx
  status (or re-raise), and a generic ``except Exception`` must never
  interpolate the caught exception into the response body — internal
  text stays in the server log.
"""

from __future__ import annotations

import ast
import os
import re
from typing import Dict, List, Optional, Set, Tuple

from rafiki_tpu.analysis import astutil
from rafiki_tpu.analysis.findings import ERROR, Finding

#: modules whose except-clauses answer HTTP requests directly
DOOR_MODULES = ("admin/http.py", "placement/agent.py",
                "predictor/server.py")

_ABSORB_RE = re.compile(r"lint:\s*absorb\s*\(")
_UNGUARDED_RE = re.compile(r"lint:\s*unguarded\s*\(")
_GUARDED_BY_RE = re.compile(r"guarded-by:\s*([A-Za-z_][A-Za-z0-9_]*)")

_LOG_METHOD_NAMES = {"debug", "info", "warning", "warn", "error",
                     "exception", "critical", "log", "print_exc"}


def package_root() -> str:
    return os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def repo_root() -> str:
    return os.path.dirname(package_root())


def lint_package(
        root: Optional[str] = None,
        env_sh_path: Optional[str] = None,
        docs_dir: Optional[str] = None,
) -> List[Finding]:
    """Run every framework pass over the package tree; returns findings
    sorted by (file, line). A clean tree returns []."""
    root = root or package_root()
    env_sh_path = env_sh_path or os.path.join(repo_root(), "scripts",
                                              "env.sh")
    docs_dir = docs_dir or os.path.join(repo_root(), "docs")
    findings: List[Finding] = []
    modules = _load_modules(root, findings)
    findings.extend(_lint_env_knobs(root, modules, env_sh_path, docs_dir))
    for rel, (tree, source, comments) in modules.items():
        findings.extend(_lint_broad_excepts(rel, tree, comments))
        findings.extend(_lint_locks(rel, tree, comments))
        if any(rel.endswith(d) for d in DOOR_MODULES):
            findings.extend(_lint_door(rel, tree, comments))
    # head 3 — the whole-package concurrency analyzer (lockset
    # inference, lock-order cycles, atomicity lint) runs over the same
    # parsed module set; its annotation grammar is documented alongside
    # the FWK disciplines in docs/static-analysis.md
    from rafiki_tpu.analysis import concurrency

    findings.extend(concurrency.analyze_modules(modules))
    findings.sort(key=lambda f: (f.file, f.line))
    return findings


def _load_modules(root: str, findings: List[Finding]
                  ) -> Dict[str, Tuple[ast.Module, str, Dict[int, str]]]:
    out: Dict[str, Tuple[ast.Module, str, Dict[int, str]]] = {}
    for dirpath, dirnames, filenames in os.walk(root):
        dirnames[:] = [d for d in dirnames
                       if d not in ("__pycache__", "web")]
        for fname in sorted(filenames):
            if not fname.endswith(".py"):
                continue
            path = os.path.join(dirpath, fname)
            rel = os.path.relpath(path, os.path.dirname(root))
            with open(path, "r", encoding="utf-8") as f:
                source = f.read()
            try:
                tree = ast.parse(source, filename=rel)
            except SyntaxError as e:
                findings.append(Finding(
                    "TPL005", f"module does not parse: {e.msg}", ERROR,
                    rel, int(e.lineno or 0)))
                continue
            out[rel] = (tree, source, astutil.comment_map(source))
    return out


# -- env-knob discipline ----------------------------------------------------

def _env_reads(tree: ast.Module) -> List[Tuple[str, int]]:
    """(name, lineno) for every constant-keyed os.environ/os.getenv
    operation whose key starts with RAFIKI_ (reads AND writes — a knob
    the platform forwards to children is still a knob)."""
    reads: List[Tuple[str, int]] = []

    def environ_chain(node: ast.AST) -> bool:
        return (astutil.dotted_name(node) or "").endswith("os.environ") \
            or (astutil.dotted_name(node) or "") == "environ"

    for node in ast.walk(tree):
        key: Optional[ast.AST] = None
        if isinstance(node, ast.Subscript) and environ_chain(node.value):
            key = node.slice
        elif isinstance(node, ast.Call):
            dotted = astutil.dotted_name(node.func) or ""
            if dotted.endswith("os.getenv") or dotted == "getenv" \
                    or (isinstance(node.func, ast.Attribute)
                        and node.func.attr in ("get", "setdefault", "pop")
                        and environ_chain(node.func.value)):
                key = node.args[0] if node.args else None
        if isinstance(key, ast.Constant) and isinstance(key.value, str) \
                and key.value.startswith("RAFIKI_"):
            reads.append((key.value, node.lineno))
    return reads


def _declared_in_config(config_source: str) -> Set[str]:
    """Every RAFIKI_* string literal in config.py declares that knob —
    the ENV_KNOBS/ENV_INTERNAL catalogs and config.py's own env reads
    all count; comments do not (a declaration is data, not prose)."""
    tree = ast.parse(config_source)
    return {n.value for n in ast.walk(tree)
            if isinstance(n, ast.Constant) and isinstance(n.value, str)
            and n.value.startswith("RAFIKI_")}


def _internal_knobs(config_source: str) -> Set[str]:
    """Names listed in config.py's ENV_INTERNAL tuple — declared
    plumbing exempt from the operator-facing env.sh/docs catalogs."""
    tree = ast.parse(config_source)
    for node in tree.body:
        if isinstance(node, ast.Assign) and any(
                isinstance(t, ast.Name) and t.id == "ENV_INTERNAL"
                for t in node.targets):
            if astutil.is_constant(node.value):
                return set(astutil.literal_value(node.value))
    return set()


def _lint_env_knobs(root: str,
                    modules: Dict[str, Tuple[ast.Module, str,
                                             Dict[int, str]]],
                    env_sh_path: str, docs_dir: str) -> List[Finding]:
    findings: List[Finding] = []
    config_rel = os.path.join(os.path.basename(root), "config.py")
    config_entry = modules.get(config_rel)
    if config_entry is None:
        return [Finding("FWK101", "config.py not found — no env-knob "
                        "declaration point", ERROR,
                        os.path.basename(root))]
    declared = _declared_in_config(config_entry[1])
    internal = _internal_knobs(config_entry[1])
    try:
        with open(env_sh_path, "r", encoding="utf-8") as f:
            env_sh = f.read()
    except OSError:
        env_sh = ""
    docs_text = ""
    if os.path.isdir(docs_dir):
        for fname in sorted(os.listdir(docs_dir)):
            if fname.endswith(".md"):
                with open(os.path.join(docs_dir, fname), "r",
                          encoding="utf-8") as f:
                    docs_text += f.read()
    reported: Set[Tuple[str, str]] = set()
    for rel, (tree, _source, _comments) in sorted(modules.items()):
        for name, lineno in _env_reads(tree):
            if name not in declared:
                if (rel, name) in reported:
                    continue
                reported.add((rel, name))
                findings.append(Finding(
                    "FWK101",
                    f"{name} is read here but not declared in config.py "
                    "— add it to ENV_KNOBS (operator knob) or "
                    "ENV_INTERNAL (platform plumbing)", ERROR, rel,
                    lineno))
                continue
            if name in internal:
                continue
            if name not in env_sh and ("env", name) not in reported:
                reported.add(("env", name))
                findings.append(Finding(
                    "FWK102",
                    f"{name} is an operator knob but scripts/env.sh "
                    "never mentions it", ERROR, rel, lineno))
            if name not in docs_text and ("docs", name) not in reported:
                reported.add(("docs", name))
                findings.append(Finding(
                    "FWK103",
                    f"{name} is an operator knob but no docs/*.md "
                    "documents it", ERROR, rel, lineno))
    return findings


# -- broad-except discipline ------------------------------------------------

def _is_broad_handler(handler: ast.ExceptHandler) -> bool:
    if handler.type is None:
        return True
    types = handler.type.elts if isinstance(handler.type, ast.Tuple) \
        else [handler.type]
    return any(astutil.terminal_name(t) in ("Exception", "BaseException")
               for t in types)


def _handler_logs_or_raises(handler: ast.ExceptHandler) -> bool:
    for node in ast.walk(handler):
        if isinstance(node, ast.Raise):
            return True
        if isinstance(node, ast.Call):
            name = astutil.terminal_name(node.func)
            if name in _LOG_METHOD_NAMES:
                return True
    return False


def _annotated(comments: Dict[int, str], lineno: int,
               pattern: re.Pattern) -> bool:
    return bool(pattern.search(comments.get(lineno, ""))
                or pattern.search(comments.get(lineno - 1, "")))


def _lint_broad_excepts(rel: str, tree: ast.Module,
                        comments: Dict[int, str]) -> List[Finding]:
    findings: List[Finding] = []
    for node in ast.walk(tree):
        if not isinstance(node, ast.ExceptHandler):
            continue
        if not _is_broad_handler(node):
            continue
        if _handler_logs_or_raises(node):
            continue
        if _annotated(comments, node.lineno, _ABSORB_RE):
            continue
        findings.append(Finding(
            "FWK201",
            "broad except absorbs the error silently — log it, "
            "re-raise, or annotate the except line with "
            "'# lint: absorb(reason)' if absorption is the contract",
            ERROR, rel, node.lineno))
    return findings


# -- lock discipline --------------------------------------------------------

def _self_attr(node: ast.AST) -> Optional[str]:
    if isinstance(node, ast.Attribute) and isinstance(node.value, ast.Name) \
            and node.value.id == "self":
        return node.attr
    return None


def _lint_locks(rel: str, tree: ast.Module,
                comments: Dict[int, str]) -> List[Finding]:
    findings: List[Finding] = []
    for cls in ast.walk(tree):
        if not isinstance(cls, ast.ClassDef):
            continue
        guarded: Dict[str, str] = {}  # attr -> lock attr
        assigned_attrs: Set[str] = set()
        for node in ast.walk(cls):
            targets: List[ast.expr] = []
            if isinstance(node, ast.Assign):
                targets = node.targets
            elif isinstance(node, (ast.AnnAssign, ast.AugAssign)):
                targets = [node.target]
            for t in targets:
                attr = _self_attr(t)
                if attr is None:
                    continue
                assigned_attrs.add(attr)
                m = _GUARDED_BY_RE.search(comments.get(node.lineno, ""))
                if m:
                    guarded[attr] = m.group(1)
        if not guarded:
            continue
        for attr, lock in guarded.items():
            if lock not in assigned_attrs:
                findings.append(Finding(
                    "FWK302",
                    f"{cls.name}.{attr} is guarded-by {lock!r} but the "
                    "class never assigns self." + lock, ERROR, rel,
                    cls.lineno))
        for method in cls.body:
            if not isinstance(method, (ast.FunctionDef,
                                       ast.AsyncFunctionDef)):
                continue
            if method.name == "__init__":
                continue
            # both lines independently — an unrelated comment on the
            # def line (# noqa) must not mask the line-above annotation
            method_holds = (
                _GUARDED_BY_RE.search(comments.get(method.lineno, ""))
                or _GUARDED_BY_RE.search(
                    comments.get(method.lineno - 1, "")))
            held_always = {method_holds.group(1)} if method_holds else set()
            findings.extend(_walk_lock_scope(
                rel, cls.name, method.body, guarded, held_always, comments))
    return findings


def _walk_lock_scope(rel: str, cls_name: str, body: List[ast.stmt],
                     guarded: Dict[str, str], held: Set[str],
                     comments: Dict[int, str]) -> List[Finding]:
    findings: List[Finding] = []
    for stmt in body:
        acquired: Set[str] = set()
        if isinstance(stmt, ast.With):
            for item in stmt.items:
                expr = item.context_expr
                if isinstance(expr, ast.Call):
                    expr = expr.func
                attr = _self_attr(expr)
                if attr is not None:
                    acquired.add(attr)
            findings.extend(_walk_lock_scope(
                rel, cls_name, stmt.body, guarded, held | acquired,
                comments))
            continue
        if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.ClassDef)):
            continue  # nested scopes opt out (closures run later)
        # check attribute uses at THIS statement's own level only —
        # nested compound bodies (incl. a `with self._lock:` under an
        # if/for/try) are handled by the recursion below, which credits
        # the locks they acquire
        for node in _own_level_nodes(stmt):
            attr = _self_attr(node)
            if attr in guarded and guarded[attr] not in held:
                if _annotated(comments, node.lineno, _UNGUARDED_RE):
                    continue
                findings.append(Finding(
                    "FWK301",
                    f"{cls_name}.{attr} is guarded-by "
                    f"{guarded[attr]!r} but accessed here without it — "
                    "wrap in 'with self." + guarded[attr] + ":', annotate "
                    "the method '# guarded-by: " + guarded[attr] + "' if "
                    "callers hold it, or '# lint: unguarded(reason)'",
                    ERROR, rel, node.lineno))
        # recurse into compound statements that are not With
        for child_body in _child_bodies(stmt):
            findings.extend(_walk_lock_scope(
                rel, cls_name, child_body, guarded, held, comments))
    return findings


def _own_level_nodes(stmt: ast.stmt):
    """Nodes evaluated at ``stmt``'s own nesting level: the statement's
    expressions (an If's test, a For's iter, an Assign's sides) but NOT
    the bodies of nested compound statements — those are separate lock
    scopes walked by the recursion."""
    for field, value in ast.iter_fields(stmt):
        if field in ("body", "orelse", "finalbody", "handlers"):
            continue
        items = value if isinstance(value, list) else [value]
        for item in items:
            if isinstance(item, ast.AST):
                yield item
                yield from ast.walk(item)


def _child_bodies(stmt: ast.stmt) -> List[List[ast.stmt]]:
    bodies = []
    for field in ("body", "orelse", "finalbody"):
        value = getattr(stmt, field, None)
        if isinstance(value, list) and value \
                and isinstance(value[0], ast.stmt):
            bodies.append(value)
    for handler in getattr(stmt, "handlers", []) or []:
        bodies.append(handler.body)
    return bodies


# -- HTTP-door discipline ---------------------------------------------------

def _respond_calls(handler_body: List[ast.stmt]) -> List[ast.Call]:
    calls = []
    for stmt in handler_body:
        for node in ast.walk(stmt):
            if isinstance(node, ast.Call):
                name = astutil.terminal_name(node.func) or ""
                if "respond" in name or name in ("send_error",
                                                 "send_response"):
                    calls.append(node)
    return calls


def _lint_door(rel: str, tree: ast.Module,
               comments: Dict[int, str]) -> List[Finding]:
    findings: List[Finding] = []
    for node in ast.walk(tree):
        if not isinstance(node, ast.ExceptHandler):
            continue
        broad = _is_broad_handler(node)
        responds = _respond_calls(node.body)
        has_raise = any(isinstance(n, ast.Raise)
                        for stmt in node.body for n in ast.walk(stmt))
        if not broad and node.type is not None:
            types = node.type.elts if isinstance(node.type, ast.Tuple) \
                else [node.type]
            typed = any((astutil.terminal_name(t) or "").endswith("Error")
                        for t in types)
            # the FWK201 escape hatch applies here too: a handler MID-
            # STREAM (chunked response already at 200) cannot answer a
            # status — its contract is the typed terminal frame, and the
            # annotation names it
            if typed and _annotated(comments, node.lineno, _ABSORB_RE):
                typed = False
            if typed and not has_raise:
                statused = any(
                    isinstance(a, ast.Constant)
                    and isinstance(a.value, int) and 400 <= a.value <= 599
                    for call in responds for a in call.args)
                if not statused:
                    findings.append(Finding(
                        "FWK401",
                        "typed error caught at an HTTP door without an "
                        "explicit 4xx/5xx response — map it to a status "
                        "or re-raise so it cannot decay into a generic "
                        "500", ERROR, rel, node.lineno))
        if broad and node.name:
            for call in responds:
                leak = astutil.contains(
                    call, lambda n: isinstance(n, ast.Name)
                    and n.id == node.name)
                if leak is not None:
                    findings.append(Finding(
                        "FWK402",
                        f"generic except interpolates {node.name!r} into "
                        "the HTTP response — internal exception text "
                        "belongs in the server log, not on the wire",
                        ERROR, rel, node.lineno))
                    break
    return findings
