"""Finding/report model shared by both static-analysis heads.

A *finding* is one rule violation at one source location; a
*VerificationReport* is the template verifier's result for one uploaded
model file — JSON-able both ways because it is persisted on the model
row (db: ``model.verification``), shipped over HTTP (``POST
/models/verify``), and printed by the CLI (``python -m
rafiki_tpu.analysis``). Codes and the annotation grammar are catalogued
in docs/static-analysis.md.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Dict, List, Optional

from rafiki_tpu.sdk.model import InvalidModelClassError

#: severities — ``error`` findings reject an upload at
#: RAFIKI_VERIFY_TEMPLATES=enforce; ``warn`` findings are surfaced but
#: never block (heuristic detectors stay warnings so a false positive
#: can never lock a working template out of the platform)
ERROR = "error"
WARN = "warn"

#: finding-code catalog (docs/static-analysis.md has the prose version).
#: Template head: TPL (structural contract), POP (PopulationSpec
#: consistency), JAX (tracing pitfalls), SBX (sandbox policy).
#: Framework head: FWK1xx env-knob discipline, FWK2xx broad-except
#: discipline, FWK3xx lock discipline, FWK4xx HTTP-door discipline.
CODES: Dict[str, str] = {
    "TPL001": "required BaseModel method missing",
    "TPL002": "knob config is not statically evaluable",
    "TPL003": "import of an undeclared non-platform dependency",
    "TPL004": "model class missing or not a BaseModel subclass",
    "TPL005": "template does not parse",
    "TPL006": "get_knob_config must be a @staticmethod",
    "TPL007": "dependencies attribute is not a literal dict",
    "SBX001": "sandbox-forbidden import",
    "POP001": "dynamic knob not present in the knob config",
    "POP002": "population_spec declared but population methods missing",
    "POP003": "Python branching on a dynamic knob in the train path",
    "POP004": "population_spec is not statically parseable",
    "GEN001": "generation_spec declared but decode methods missing",
    "GEN002": "generation decode method has an inconsistent signature",
    "GEN003": "generation_spec is not statically parseable",
    "JAX001": "host sync (.item()/float()/np.asarray) on a traced value",
    "JAX002": "legacy global numpy.random API (thread PRNG keys instead)",
    "JAX003": "mutation of self state inside a jit/vmap-traced function",
    "JAX004": "recompile risk: jit over loop-varying or per-request values",
    "CONC101": "shared attribute written outside its inferred lock",
    "CONC102": "branch decided by a read outside the inferred lock",
    "CONC201": "lock-order cycle / re-acquire — potential deadlock",
    "CONC301": "check-then-act on a shared attribute without a lock",
    "CONC302": "read-modify-write on a shared attribute without a lock",
    "FWK101": "RAFIKI_* env read not declared in config.py",
    "FWK102": "RAFIKI_* env knob not catalogued in scripts/env.sh",
    "FWK103": "RAFIKI_* env knob not documented under docs/",
    "FWK201": "broad except absorbs silently (log, re-raise, or annotate)",
    "FWK301": "guarded-by attribute accessed outside its lock",
    "FWK302": "guarded-by annotation names a lock the class never creates",
    "FWK401": "typed error caught at an HTTP door without a status response",
    "FWK402": "HTTP door leaks exception text on a generic except",
}


@dataclasses.dataclass
class Finding:
    code: str
    message: str
    severity: str = ERROR
    file: str = "<uploaded>"
    line: int = 0
    col: int = 0

    def to_dict(self) -> Dict[str, Any]:
        return dataclasses.asdict(self)

    @classmethod
    def from_dict(cls, d: Dict[str, Any]) -> "Finding":
        return cls(**{f.name: d[f.name] for f in dataclasses.fields(cls)
                      if f.name in d})

    def __str__(self) -> str:
        loc = f"{self.file}:{self.line}" if self.line else self.file
        return f"{loc}: {self.severity} {self.code}: {self.message}"


class VerificationReport:
    """The template verifier's verdict for one model source file."""

    def __init__(self, class_name: Optional[str] = None,
                 findings: Optional[List[Finding]] = None,
                 capabilities: Optional[Dict[str, Any]] = None):
        self.class_name = class_name
        self.findings: List[Finding] = list(findings or [])
        #: statically-derived capability verdicts — the single oracle
        #: replacing ad-hoc source sniffs (doctor's vmap probe):
        #: {"population": bool, "population_spec": {...}|None}
        self.capabilities: Dict[str, Any] = dict(capabilities or {})

    @property
    def errors(self) -> List[Finding]:
        return [f for f in self.findings if f.severity == ERROR]

    @property
    def warnings(self) -> List[Finding]:
        return [f for f in self.findings if f.severity == WARN]

    @property
    def ok(self) -> bool:
        """True when nothing blocks an enforce-mode upload."""
        return not self.errors

    def add(self, code: str, message: str, severity: str = ERROR,
            file: str = "<uploaded>", line: int = 0, col: int = 0) -> None:
        self.findings.append(Finding(code, message, severity, file, line, col))

    def to_dict(self) -> Dict[str, Any]:
        return {
            "ok": self.ok,
            "class_name": self.class_name,
            "capabilities": self.capabilities,
            "findings": [f.to_dict() for f in self.findings],
        }

    @classmethod
    def from_dict(cls, d: Dict[str, Any]) -> "VerificationReport":
        return cls(
            class_name=d.get("class_name"),
            findings=[Finding.from_dict(f) for f in d.get("findings", [])],
            capabilities=d.get("capabilities") or {},
        )

    def summary(self) -> str:
        if not self.findings:
            return "clean"
        return (f"{len(self.errors)} error(s), "
                f"{len(self.warnings)} warning(s)")


class ModelVerificationError(InvalidModelClassError):
    """An enforce-mode upload was rejected by the template verifier.

    Subclasses InvalidModelClassError so every existing HTTP door maps it
    to 400 with zero new wiring; carries the full report for clients that
    want the finding list (``Client.verify_model`` is the dry-run path)."""

    def __init__(self, report: VerificationReport):
        self.report = report
        lines = "; ".join(str(f) for f in report.errors[:5])
        more = len(report.errors) - 5
        if more > 0:
            lines += f" (+{more} more)"
        super().__init__(
            f"model template failed static verification "
            f"({report.summary()}): {lines}")
