"""Elastic serving autoscaler: the closed loop over the telemetry plane.

PRs 1-6 gave the serving plane every signal a controller needs — per-door
shed counters and EWMA-wait rings (predictor/admission.py), per-job shed
rings and live backlog (predictor/predictor.py), per-service queue-depth
rings (worker/inference.py) — but replica counts stayed frozen at
``create_inference_services`` time. This module closes the loop:

- an admin-side **control thread** (``RAFIKI_AUTOSCALE=1``) ticks every
  ``RAFIKI_AUTOSCALE_INTERVAL_S`` seconds, samples each RUNNING inference
  job's backlog into its own ring series (``backlog:job:<id>``), reads
  the job's shed deltas, and decides;
- **scale up** on sustained overload — shed events past
  ``RAFIKI_AUTOSCALE_SHED_THRESHOLD`` inside the window, or mean backlog
  past ``RAFIKI_AUTOSCALE_DEPTH_HIGH`` — bounded by
  ``RAFIKI_AUTOSCALE_MAX_REPLICAS`` and ``RAFIKI_AUTOSCALE_STEP``;
- **scale down** on sustained idle — zero shed and backlog never above
  ``RAFIKI_AUTOSCALE_DEPTH_LOW`` across the whole window — bounded by
  ``RAFIKI_AUTOSCALE_MIN_REPLICAS``, executed as a graceful drain
  (admin/services.py ``drain_replicas``: retire from the fan-out, flush
  the queue — for generation replicas, also wait out resident streams —
  then destroy; zero in-flight requests dropped, and streams that can't
  finish in the drain window are handed back typed MIGRATING for
  door-side resume on siblings, docs/failure-model.md "Stream
  continuity");
- **hysteresis + cooldowns** (`DEPTH_LOW` well under `DEPTH_HIGH`;
  separate up/down cooldowns, down much longer) and the bounded step so
  the loop can never flap or stampede;
- **chip-budget arbitration**: a scale-up borrows idle trial chips
  through the ChipBudgetArbiter (placement/hosts.py) when the training
  floor allows; training reclaims the loan on demand.

Every decision is a first-class event — reason + signal snapshot —
kept in a bounded log surfaced via ``GET /fleet/health`` ("autoscaler"
section) and counted in ``/metrics``
(``rafiki_autoscale_{up,down}_total``, ``rafiki_autoscale_borrowed_chips``).

Reference analogue: none. The reference's serving fleet was whatever
``docker service create`` was told at deploy time, forever (reference
services_manager.py:53-87) — SURVEY §2.10's "inference replica
parallelism" was a constant, not a controller.
"""

from __future__ import annotations

import collections
import logging
import threading
import time
from typing import Any, Deque, Dict, List, Optional

from rafiki_tpu import config
from rafiki_tpu.constants import InferenceJobStatus

logger = logging.getLogger(__name__)


class Autoscaler:
    """One per Admin. The loop thread only runs when ``RAFIKI_AUTOSCALE=1``
    (or :meth:`start` is called explicitly); a stopped instance still
    answers :meth:`report` so /fleet/health always has the section."""

    def __init__(self, admin) -> None:
        self._admin = admin
        self._services = admin.services
        self._db = admin.db
        self._arbiter = getattr(admin, "chip_arbiter", None)
        self._closed = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._lock = threading.Lock()
        # per-job controller state: signal history + cooldown bookkeeping
        # {job_id: {"history": deque[(ts, shed_delta, backlog)],
        #           "last_shed_total": int, "last_action_ts": float,
        #           "last_action": str}}
        self._jobs: Dict[str, Dict[str, Any]] = {}  # guarded-by: _lock
        #: bounded decision log, newest last (fleet-health "autoscaler");
        #: append (tick thread) and snapshot (/fleet/health thread) race —
        #: iterating a deque during an append raises RuntimeError
        self.events: Deque[Dict[str, Any]] = (  # guarded-by: _lock
            collections.deque(maxlen=100))
        from rafiki_tpu.utils.metrics import REGISTRY

        self._registry = REGISTRY
        self._m_up = REGISTRY.counter(
            "rafiki_autoscale_up_total",
            "autoscaler scale-up actions", ("job",))
        self._m_down = REGISTRY.counter(
            "rafiki_autoscale_down_total",
            "autoscaler scale-down actions", ("job",))
        self._m_ticks = REGISTRY.counter(
            "rafiki_autoscale_ticks_total",
            "autoscaler control-loop ticks")

    # -- lifecycle ----------------------------------------------------------

    @property
    def running(self) -> bool:
        return self._thread is not None and self._thread.is_alive()

    def start(self) -> "Autoscaler":
        if self.running:
            return self
        self._closed.clear()
        self._thread = threading.Thread(
            target=self._loop, name="autoscaler", daemon=True)
        self._thread.start()
        logger.info("autoscaler loop started (interval %.1fs, window "
                    "%.1fs)", float(config.AUTOSCALE_INTERVAL_S),
                    float(config.AUTOSCALE_WINDOW_S))
        return self

    def stop(self) -> None:
        self._closed.set()
        t = self._thread
        if t is not None:
            # a tick may legitimately sit inside a graceful drain or a
            # scale-up's deploy wait; cover both windows plus slack so a
            # surviving tick can't race the teardown that follows stop()
            t.join(timeout=float(config.AUTOSCALE_DRAIN_S)
                   + float(config.SERVICE_DEPLOY_TIMEOUT_S) + 10)
        self._thread = None

    def _loop(self) -> None:
        while not self._closed.wait(float(config.AUTOSCALE_INTERVAL_S)):
            try:
                self.tick()
            except Exception:
                logger.exception("autoscaler tick failed")

    # -- the control loop ---------------------------------------------------

    def tick(self) -> List[Dict[str, Any]]:
        """One decision pass over every live inference job. Public and
        synchronous so tests (and an operator REPL) can drive the loop
        deterministically. Returns the decisions taken this tick."""
        self._m_ticks.inc()
        now = time.monotonic()
        actions: List[Dict[str, Any]] = []
        predictors = self._services.predictors()
        with self._lock:
            # forget controller state for jobs that no longer serve
            for job_id in list(self._jobs):
                if job_id not in predictors:
                    del self._jobs[job_id]
        for job_id, predictor in predictors.items():
            if self._closed.is_set():
                break  # shutting down: no new decisions mid-teardown
            try:
                action = self._tick_job(job_id, predictor, now)
            except Exception:
                logger.exception("autoscaler decision for job %s failed",
                                 job_id[:8])
                continue
            if action is not None:
                actions.append(action)
        return actions

    def _job_state(self, job_id: str) -> Dict[str, Any]:
        with self._lock:
            st = self._jobs.get(job_id)
            if st is None:
                st = self._jobs[job_id] = {
                    "history": collections.deque(maxlen=512),
                    "last_shed_total": None,
                    "last_action_ts": 0.0,
                    "last_action": None,
                }
            return st

    def _shed_total(self, job_id: str, predictor) -> int:
        """The job's cumulative shed count across every shed site that
        names it: predictor-level request/trial sheds plus — when the job
        has a dedicated door — that door's admission sheds."""
        ov = predictor.overload_stats()
        total = int(ov.get("requests_shed", 0)) + int(
            ov.get("trials_shed", 0))
        psrv = self._services._predict_servers.get(job_id)
        admission = getattr(psrv, "admission", None)
        if admission is not None:
            s = admission.stats()
            total += int(s.get("shed_capacity", 0))
            total += int(s.get("shed_deadline", 0))
            total += int(s.get("shed_fairness", 0))
        return total

    @staticmethod
    def _cache_hit_rate(job_id: str, st: Dict[str, Any]
                        ) -> Optional[float]:
        """Hit rate of the job's prediction cache since the previous
        tick (None while the cache serves nothing — keeps pre-cache
        decision records byte-stable)."""
        try:
            from rafiki_tpu.predictor.result_cache import get_cache

            hits, misses = get_cache().job_totals(job_id)
        # lint: absorb(cache totals are a best-effort signal annotation)
        except Exception:
            return None
        last = st.get("last_cache_totals")
        st["last_cache_totals"] = (hits, misses)
        if last is None:
            return None
        dh, dm = hits - last[0], misses - last[1]
        if dh + dm <= 0:
            return None
        return round(dh / (dh + dm), 3)

    def _tick_job(self, job_id: str, predictor,
                  now: float) -> Optional[Dict[str, Any]]:
        inf = self._db.get_inference_job(job_id)
        if inf is None or inf["status"] != InferenceJobStatus.RUNNING:
            return None
        st = self._job_state(job_id)
        # a rollout mid-flight owns this job's replica set: the
        # controller is deliberately adding/draining replicas, and a
        # concurrent autoscale decision would fight it (drain the canary,
        # or read the rolling replace's churn as load). Pause decisions
        # and clear the window, so the first post-rollout decision is
        # made on a fresh window over the NEW fleet, never on
        # mid-rollout churn (getattr: the controller is wired right
        # after this object in the Admin constructor).
        rollouts = getattr(self._admin, "rollouts", None)
        if rollouts is not None and rollouts.is_active(job_id):
            st["history"].clear()
            st["last_shed_total"] = None
            return None
        # -- sample signals ------------------------------------------------
        try:
            backlog = int(predictor.backlog_depth())
        # lint: absorb(backlog sample is best-effort; 0 skips this tick)
        except Exception:
            backlog = 0
        # observable twin of the internal history: a bounded ring series
        # anyone can read off GET /metrics?format=json
        self._registry.ring(f"backlog:job:{job_id}").record(backlog)
        shed_total = self._shed_total(job_id, predictor)
        last = st["last_shed_total"]
        shed_delta = max(shed_total - last, 0) if last is not None else 0
        st["last_shed_total"] = shed_total
        st["history"].append((now, shed_delta, backlog))
        # -- windowed view -------------------------------------------------
        window_s = max(float(config.AUTOSCALE_WINDOW_S), 1.0)
        window = [(t, s, b) for t, s, b in st["history"]
                  if now - t <= window_s]
        if not window:
            return None
        shed_in_window = sum(s for _, s, _ in window)
        depths = [b for _, _, b in window]
        mean_depth = sum(depths) / len(depths)
        max_depth = max(depths)
        span_s = now - window[0][0]
        live = self._services.live_inference_workers(job_id)
        n_live = len(live)
        # generative jobs (worker/generation.py): queue depth alone
        # under-reads their load — admitted streams occupy decode memory
        # for hundreds of steps while the queue sits near empty. The
        # workers publish a per-job occupancy ring (fraction of the
        # BINDING resource: KV-pool blocks under the paged allocator,
        # busy slots under the legacy ring — a few long streams can
        # exhaust the pool with the slot table half empty, so block
        # occupancy is what predicts the next admission stalling);
        # sustained-high occupancy is the generation-plane overload
        # signal, symmetric with backlog depth for the one-shot plane.
        wall_now = time.time()
        occ = [v for t, v in
               self._registry.ring(f"slot_occupancy:job:{job_id}").series()
               if wall_now - t <= window_s]
        mean_occ = (sum(occ) / len(occ)) if occ else 0.0
        max_occ = max(occ) if occ else 0.0
        signals = {
            "shed_in_window": shed_in_window,
            "mean_backlog": round(mean_depth, 2),
            "max_backlog": max_depth,
            "window_span_s": round(span_s, 2),
            "replicas": n_live,
        }
        if occ:
            signals["slot_occupancy"] = round(mean_occ, 2)
        # prediction-cache hit rate since the last tick
        # (predictor/result_cache.py): purely a decision-record
        # annotation — backlog and shed already measure MISS load by
        # construction (hits never touch a queue or shed anyone), which
        # is exactly why the loop stops flapping when the cache is on.
        # The operator reading a scale event should see what the cache
        # absorbed alongside what leaked through.
        hit_rate = self._cache_hit_rate(job_id, st)
        if hit_rate is not None:
            signals["cache_hit_rate"] = hit_rate
        # -- decide --------------------------------------------------------
        step = max(int(config.AUTOSCALE_STEP), 1)
        since_action = now - st["last_action_ts"]
        occ_high = float(config.GEN_OCCUPANCY_HIGH)
        overloaded = (
            shed_in_window >= max(int(config.AUTOSCALE_SHED_THRESHOLD), 1)
            or mean_depth >= float(config.AUTOSCALE_DEPTH_HIGH)
            or (bool(occ) and mean_occ >= occ_high))
        idle = (shed_in_window == 0
                and max_depth <= float(config.AUTOSCALE_DEPTH_LOW)
                # saturated generation slots hold the floor even with an
                # empty queue (half of HIGH = comfortably unsaturated)
                and max_occ <= occ_high / 2)
        if overloaded and n_live < int(config.AUTOSCALE_MAX_REPLICAS):
            if since_action < float(config.AUTOSCALE_COOLDOWN_UP_S):
                return None
            step = min(step, int(config.AUTOSCALE_MAX_REPLICAS) - n_live)
            if shed_in_window >= int(config.AUTOSCALE_SHED_THRESHOLD):
                reason = "sustained shed"
            elif mean_depth >= float(config.AUTOSCALE_DEPTH_HIGH):
                reason = "sustained backlog depth"
            else:
                reason = "generation slot occupancy"
            return self._act(job_id, st, "scale_up", step, reason,
                             signals)
        if idle and n_live > int(config.AUTOSCALE_MIN_REPLICAS):
            # a scale-down needs the window to actually COVER idle time:
            # a single fresh sample after a restart must not drain anyone
            if span_s < window_s * 0.6:
                return None
            if since_action < float(config.AUTOSCALE_COOLDOWN_DOWN_S):
                return None
            step = min(step, n_live - int(config.AUTOSCALE_MIN_REPLICAS))
            return self._act(job_id, st, "scale_down", step,
                             "sustained idle", signals)
        return None

    def _act(self, job_id: str, st: Dict[str, Any], action: str,
             step: int, reason: str,
             signals: Dict[str, Any]) -> Optional[Dict[str, Any]]:
        if self._closed.is_set():
            return None  # never place or drain after stop() was signalled
        delta = step if action == "scale_up" else -step
        try:
            report = self._services.scale_inference_job(
                job_id, delta,
                min_replicas=int(config.AUTOSCALE_MIN_REPLICAS))
        except Exception as e:
            logger.warning("autoscaler %s of job %s failed: %s",
                           action, job_id[:8], e)
            report = {"error": str(e)}
        st["last_action_ts"] = time.monotonic()
        st["last_action"] = action
        # the headline counters mean "scaling happened" — a failed
        # attempt is visible as the event's result.error, not a count
        acted = bool(report.get("added") or report.get("removed"))
        if acted:
            # a scale-up served from the warm standby pool is a routing
            # flip, not a deploy — name it so operators reading the event
            # stream can tell elasticity-by-promotion from cold placement
            if report.get("promoted"):
                reason += " (served by warm-pool promotion)"
            if action == "scale_up":
                self._m_up.labels(job_id).inc()
            else:
                self._m_down.labels(job_id).inc()
            # a fresh capacity level deserves a fresh observation window:
            # the burst that justified THIS action must not be re-counted
            # into the next decision (cooldown < window, so without the
            # reset one resolved burst keeps scaling until MAX_REPLICAS)
            st["history"].clear()
        event = {
            "ts": time.time(),
            "job_id": job_id,
            "action": action,
            "delta": delta,
            "reason": reason,
            "signals": signals,
            "result": report,
        }
        # appended under the lock: report() (the /fleet/health thread)
        # snapshots the deque concurrently, and iterating a deque while
        # another thread appends raises RuntimeError
        with self._lock:
            self.events.append(event)
        logger.warning("autoscaler %s job %s by %d (%s; signals=%s)",
                       action, job_id[:8], abs(delta), reason, signals)
        return event

    # -- observability ------------------------------------------------------

    def report(self) -> Dict[str, Any]:
        """The fleet-health "autoscaler" section: loop state, config
        snapshot, chip-loan picture, recent decisions."""
        with self._lock:
            jobs = {
                job_id: {
                    "last_action": st["last_action"],
                    "samples": len(st["history"]),
                }
                for job_id, st in self._jobs.items()
            }
            recent_events = list(self.events)[-20:]
        arbiter = {}
        if self._arbiter is not None:
            total, free = self._arbiter.capacity()
            arbiter = {
                "borrowed_chips": self._arbiter.borrowed_chips(),
                "borrowed_by_service": {
                    sid[:8]: n
                    for sid, (_, n) in self._arbiter.borrowed().items()},
                "train_floor_chips": self._arbiter.floor(),
                "total_chips": total,
                "free_chips": free,
            }
        return {
            "enabled": bool(config.AUTOSCALE),
            "running": self.running,
            "fair_admission": bool(config.AUTOSCALE_FAIR),
            "interval_s": float(config.AUTOSCALE_INTERVAL_S),
            "window_s": float(config.AUTOSCALE_WINDOW_S),
            "bounds": {
                "min_replicas": int(config.AUTOSCALE_MIN_REPLICAS),
                "max_replicas": int(config.AUTOSCALE_MAX_REPLICAS),
                "step": int(config.AUTOSCALE_STEP),
            },
            "jobs": jobs,
            "chip_budget": arbiter,
            "events": recent_events,
        }
