"""Orchestration core (L5): job lifecycle + service deployment
(reference rafiki/admin/)."""

from rafiki_tpu.admin.admin import Admin  # noqa: F401
from rafiki_tpu.admin.services import ServicesManager  # noqa: F401
