"""Control-plane crash recovery: admin restart reconciliation.

`ServicesManager` holds predictors, predict servers, and placement state
purely in memory, so an admin crash used to strand the store: jobs pinned
at RUNNING forever while workers on surviving host agents kept serving
and training unmanaged — the gap Rafiki inherited from its reference
(admin state outside the metadata DB) and the classic reconcile-on-restart
problem of Borg/Kubernetes-style controllers (PAPERS.md). A fresh
:class:`~rafiki_tpu.admin.admin.Admin` now boots idempotently:

1. **Scan** — one query (``Database.get_non_terminal_services``) snapshots
   every non-terminal service joined to its job linkage, plus the
   non-terminal job rows. The snapshot is taken synchronously in the
   Admin constructor, so state created *after* boot is never reconciled.
2. **Probe** — every registered host agent answers ``GET /inventory``
   with the services it is actually running (bounded by
   ``RAFIKI_RECOVER_PROBE_TIMEOUT_S``).
3. **Reconcile** (off-thread, behind a ``recovering -> ready`` admin
   state that 503s the HTTP doors):
   - **fence** orphans — services still running whose DB row or job went
     terminal while the admin was down (one service id, one executor);
   - **adopt** survivors — placement state rebuilt from the store
     (relay queues re-registered, `Predictor`/`PredictorServer`
     reconstructed, so ``predict()`` answers without a redeploy); local
     process-mode children are adopted by pid. ``RAFIKI_RECOVER_ADOPT=0``
     turns every would-be adoption into a fence;
   - **reschedule** train services whose hosts died, through the PR-1
     failover machinery (same service id -> stale-trial resume);
   - **error** the truly unrecoverable, with a recorded reason, through
     the admin's status callback so job-level refresh fires.
4. **Sweep** — every non-terminal job is refreshed; a job left with zero
   live services is terminal-ized (no DB row may survive recovery in a
   non-terminal status with nothing backing it).

Metadata-store hiccups during any step retry with bounded jittered
backoff (drillable via ``RAFIKI_CHAOS`` ``site=db``) instead of aborting
recovery. The final report is surfaced under ``recovery`` in
``GET /fleet/health`` and persisted to ``<logs>/recovery.json`` for the
doctor.
"""

from __future__ import annotations

import json
import logging
import os
import random
import re
import threading
import time
from typing import Any, Dict, List, Optional

from rafiki_tpu import config
from rafiki_tpu.constants import (
    InferenceJobStatus,
    ServiceStatus,
    ServiceType,
    TrainJobStatus,
    TrialStatus,
)

logger = logging.getLogger(__name__)

_TERMINAL = (ServiceStatus.STOPPED, ServiceStatus.ERRORED)
_JOB_TERMINAL = (TrainJobStatus.STOPPED, TrainJobStatus.ERRORED)
_MAX_REASONS = 64  # the report is an operator view, not a log archive

REPORT_FILENAME = "recovery.json"


class RecoveryAborted(Exception):
    """The admin is shutting down: reconciliation must stop placing
    things NOW — a service re-placed after teardown has nothing left to
    ever stop it."""


def report_path() -> str:
    return os.path.join(config.LOGS_DIR, REPORT_FILENAME)


def _job_status_of(row: Dict[str, Any]) -> Optional[str]:
    """The governing job status for a service row from the recovery scan
    (None = no job linkage at all)."""
    if row["service_type"] == ServiceType.TRAIN:
        return row.get("train_job_status")
    if row["service_type"] == ServiceType.INFERENCE:
        return row.get("inference_job_status")
    if row["service_type"] == ServiceType.PREDICT:
        return row.get("predictor_job_status")
    return None


def _extra_of(row: Dict[str, Any]) -> Dict[str, Any]:
    """Rebuild the placement payload from the store row — the declarative
    `extra` a placement engine needs to relaunch (or adopt) the worker."""
    if row["service_type"] == ServiceType.TRAIN:
        return {"sub_train_job_id": row.get("sub_train_job_id")}
    if row["service_type"] == ServiceType.INFERENCE:
        return {"inference_job_id": row.get("inference_job_id"),
                "trial_id": row.get("trial_id")}
    return {}


class ControlPlaneRecovery:
    """One boot-time reconciliation pass for an Admin."""

    def __init__(self, admin):
        self.admin = admin
        self.db = admin.db
        self.report: Dict[str, Any] = {
            "state": "recovering",
            "started_at": time.time(),
            "scanned": 0,
            "adopted": 0,
            "rescheduled": 0,
            "fenced": 0,
            "closed": 0,
            "errored": 0,
            "jobs_closed": 0,
            "agents_probed": 0,
            "agents_unreachable": 0,
            "db_retries": 0,
            "reasons": [],
        }
        self._restored_advisors: set = set()
        #: set by Admin.shutdown(): checked at every loop top and inside
        #: retry backoffs, so a reconcile can never re-place a service
        #: after teardown started
        self._abort = threading.Event()

    def abort(self) -> None:
        self._abort.set()

    def _check_abort(self) -> None:
        if self._abort.is_set():
            raise RecoveryAborted("admin is shutting down")

    # -- bounded-retry store access ---------------------------------------

    def _retry(self, fn, what: str):
        """Run a metadata-store step with bounded jittered backoff — a
        transient store failure (drill: RAFIKI_CHAOS site=db) must not
        abort recovery and leave the fleet unreconciled."""
        attempts = max(int(config.RECOVER_RETRY_MAX), 0) + 1
        for attempt in range(attempts):
            self._check_abort()
            try:
                return fn()
            except RecoveryAborted:
                raise
            except Exception as e:
                if attempt + 1 >= attempts:
                    raise
                self.report["db_retries"] += 1
                delay = (float(config.RECOVER_RETRY_BACKOFF_S)
                         * (2 ** attempt) * random.uniform(0.5, 1.5))
                logger.warning(
                    "recovery: %s failed (%s); retry %d/%d in %.2fs",
                    what, e, attempt + 1, attempts - 1, delay)
                if self._abort.wait(delay):
                    raise RecoveryAborted("admin is shutting down")

    def _reason(self, text: str) -> None:
        if len(self.report["reasons"]) < _MAX_REASONS:
            self.report["reasons"].append(text)

    # -- snapshot (synchronous, in the Admin constructor) ------------------

    def snapshot(self) -> Dict[str, Any]:
        from rafiki_tpu.constants import RolloutPhase

        services = self._retry(self.db.get_non_terminal_services,
                               "service scan")
        train_jobs = self._retry(
            lambda: self.db.get_train_jobs_by_statuses(
                [TrainJobStatus.STARTED, TrainJobStatus.RUNNING]),
            "train-job scan")
        inference_jobs = self._retry(
            lambda: self.db.get_inference_jobs_by_statuses(
                [InferenceJobStatus.STARTED, InferenceJobStatus.RUNNING]),
            "inference-job scan")
        # CANARY/ROLLING rollout rows force a reconcile even when every
        # job row happens to be terminal (e.g. the job was stopped while
        # the admin was down): a live rollout row must always be
        # resolved, never stranded
        rollouts = self._retry(
            lambda: self.db.get_rollouts_by_phases(
                list(RolloutPhase.LIVE)),
            "rollout scan")
        return {"services": services, "train_jobs": train_jobs,
                "inference_jobs": inference_jobs, "rollouts": rollouts}

    @staticmethod
    def needed(snapshot: Dict[str, Any]) -> bool:
        return any(snapshot.get(k) for k in
                   ("services", "train_jobs", "inference_jobs",
                    "rollouts"))

    def empty_report(self) -> Dict[str, Any]:
        return {**self.report, "state": "ready", "duration_s": 0.0}

    # -- reconciliation (off-thread) ---------------------------------------

    def run(self, snapshot: Dict[str, Any]) -> Dict[str, Any]:
        t0 = time.monotonic()
        try:
            self._reconcile(snapshot)
        except Exception as e:
            # an aborted reconcile must be VISIBLE — in memory AND in the
            # persisted report doctor reads — never dressed up as a clean
            # pass with partial counts. The doors still open (a failed
            # reconcile must not brick the admin); doctor flags the rest.
            self.report["failed"] = True
            self.report["error"] = f"{type(e).__name__}: {e}"
            self._reason(f"reconciliation ABORTED: {type(e).__name__}: {e}")
            logger.exception("control-plane reconciliation aborted")
        self.report["state"] = "ready"
        self.report["duration_s"] = round(time.monotonic() - t0, 3)
        self._persist_report()
        logger.info(
            "control-plane recovery done in %.2fs: %d scanned, %d adopted, "
            "%d rescheduled, %d fenced, %d errored%s",
            self.report["duration_s"], self.report["scanned"],
            self.report["adopted"], self.report["rescheduled"],
            self.report["fenced"], self.report["errored"],
            " (ABORTED)" if self.report.get("failed") else "")
        return dict(self.report)

    def _reconcile(self, snapshot: Dict[str, Any]) -> None:
        admin = self.admin
        placement = admin.placement
        services: List[Dict[str, Any]] = snapshot["services"]
        self.report["scanned"] = len(services)
        by_id = {s["id"]: s for s in services}
        adopt_enabled = bool(config.RECOVER_ADOPT)
        if not adopt_enabled:
            self._reason("RAFIKI_RECOVER_ADOPT=0: surviving workers are "
                         "fenced, not adopted")

        # -- rebuild advisor sessions FIRST: a surviving train worker may
        # hit POST /advisors/<sub_id>/propose at any moment (that route
        # rides through the recovering gate on purpose), so every
        # non-terminal train service's session must exist before the
        # slower probe/adopt passes run
        for row in services:
            if (row["service_type"] == ServiceType.TRAIN
                    and row.get("train_job_status") not in _JOB_TERMINAL
                    and row.get("sub_train_job_id")):
                self._restore_advisor(row["sub_train_job_id"])

        # -- probe agents for ground truth --------------------------------
        running_on: Dict[str, str] = {}  # service_id -> agent addr
        inventories: Dict[str, Optional[Dict[str, Any]]] = {}
        if hasattr(placement, "probe_inventories"):
            inventories = placement.probe_inventories()
            self.report["agents_probed"] = len(inventories)
            self.report["agents_unreachable"] = sum(
                1 for v in inventories.values() if v is None)
            for addr, inv in inventories.items():
                for entry in (inv or {}).get("services", []):
                    running_on[entry["service_id"]] = addr

        # -- fence: running orphans whose DB row/job went terminal, or
        # whose row lost its job linkage entirely -------------------------
        for addr, inv in inventories.items():
            for entry in (inv or {}).get("services", []):
                sid = entry["service_id"]
                row = by_id.get(sid)
                jstatus = _job_status_of(row) if row else None
                if row is not None and jstatus is not None \
                        and jstatus not in _JOB_TERMINAL:
                    continue  # a live, legitimately-owned service
                if row is None:
                    # not in the boot snapshot — either terminal/missing
                    # (an orphan) or created AFTER this admin booted by an
                    # in-process caller racing the off-thread reconcile.
                    # Re-read the LIVE row: a non-terminal row proves the
                    # service is this admin's own fresh placement, never
                    # an orphan to fence.
                    try:
                        fresh = self._retry(
                            lambda s=sid: self.db.get_service(s),
                            f"live re-check of {sid[:8]}")
                    except RecoveryAborted:
                        raise
                    # lint: absorb(cannot prove orphanhood after retries; leave the row alone)
                    except Exception:
                        continue  # cannot prove orphanhood: do nothing
                    if fresh is not None and fresh["status"] not in _TERMINAL:
                        # also off-limits for every later pass (the
                        # adoption-disabled fence sweep included): this
                        # is NOT a survivor of the dead admin
                        running_on.pop(sid, None)
                        continue
                why = ("no (or terminal) store row" if row is None
                       else "no job row references it"
                       if jstatus is None else f"its job is {jstatus}")
                fenced = (hasattr(placement, "fence_service")
                          and placement.fence_service(sid, addr))
                running_on.pop(sid, None)
                # either way this service must not be adopted/rescheduled
                # below; but its row is only CLOSED when the fence landed
                # — a row closed over a still-running executor would hide
                # the orphan from doctor and every future reconcile
                by_id.pop(sid, None)
                if fenced:
                    self.report["fenced"] += 1
                    self._reason(f"{sid[:8]}: fenced on {addr} ({why})")
                    if row is not None:
                        self._retry(
                            lambda s=sid: self.db.mark_service_as_stopped(s),
                            f"close fenced row {sid[:8]}")
                elif row is not None:
                    self._reason(
                        f"{sid[:8]}: could not fence on {addr} ({why}); "
                        "row left non-terminal for the next reconcile")

        if not adopt_enabled:
            # adoption disabled: every survivor is fenced; a fenced
            # service is then treated as host-dead below (reschedule/
            # error), while a FAILED fence leaves it untouched — acting
            # on a possibly-still-running executor could double-run it.
            # wait=True: a TRAIN service may be re-placed under the SAME
            # id right below, so the old executor must be provably gone
            for sid, addr in list(running_on.items()):
                if hasattr(placement, "fence_service") and \
                        placement.fence_service(sid, addr, wait=True):
                    self.report["fenced"] += 1
                else:
                    by_id.pop(sid, None)
                    self._reason(f"{sid[:8]}: could not fence on {addr} "
                                 "(adoption disabled); left untouched")
            running_on.clear()

        # -- adopt / reschedule / error every non-terminal service --------
        adopted_serving_jobs = set()
        unreachable = [a for a, inv in inventories.items() if inv is None]
        for row in services:
            self._check_abort()
            sid = row["id"]
            if sid not in by_id:
                continue  # already closed by the fence pass
            stype = row["service_type"]
            jstatus = _job_status_of(row)
            if jstatus in _JOB_TERMINAL:
                # the job finished/was stopped while the admin was down,
                # and nothing is running for it: close the stale row
                self._retry(
                    lambda s=sid: self.db.mark_service_as_stopped(s),
                    f"close stale row {sid[:8]}")
                self.report["closed"] += 1
                continue
            if stype == ServiceType.PREDICT:
                continue  # serving heads are rebuilt per-job below
            if jstatus is None:
                self._error_service(
                    sid, "no job row references this service "
                         "(orphaned linkage)")
                continue
            extra = _extra_of(row)
            n_chips = len(row.get("chips") or [])
            addr = running_on.get(sid)
            if addr is not None and hasattr(placement, "adopt_service"):
                if placement.adopt_service(
                        sid, addr, stype, n_chips=n_chips, extra=extra,
                        best_effort_chips=(stype == ServiceType.INFERENCE)):
                    self.report["adopted"] += 1
                    if stype == ServiceType.INFERENCE:
                        adopted_serving_jobs.add(extra["inference_job_id"])
                        self._readopt_chip_loan(row, extra)
                    continue
            if adopt_enabled and self._adopt_local_pid(row, extra):
                self.report["adopted"] += 1
                if stype == ServiceType.INFERENCE:
                    adopted_serving_jobs.add(extra["inference_job_id"])
                    self._readopt_chip_loan(row, extra)
                continue
            if not adopt_enabled:
                # surviving LOCAL children must be fenced before anything
                # is re-placed under their id (SIGTERM + bounded wait,
                # identity-pinned) — 'RAFIKI_RECOVER_ADOPT=0 fences all
                # survivors' holds on single-host placements too
                self._fence_local_survivor(row)
            # nothing is running this service anymore: its host (or the
            # whole single-host process tree) died
            if stype == ServiceType.TRAIN:
                if unreachable and hasattr(placement,
                                           "quarantine_on_rejoin"):
                    # BEFORE re-placing the id: the old executor MAY still
                    # run on an agent whose probe merely timed out — fence
                    # it there the moment that agent proves alive (or now,
                    # if it already rejoined)
                    placement.quarantine_on_rejoin(unreachable, sid)
                if self._restart_train(row, extra, n_chips,
                                       exclude=unreachable):
                    self.report["rescheduled"] += 1
                else:
                    self._error_service(
                        sid, "train executor lost (host died while the "
                             "control plane was down; no capacity to "
                             "reschedule)")
            else:
                if unreachable and hasattr(placement,
                                           "quarantine_on_rejoin"):
                    # same rule for an errored replica: if its host was
                    # only slow, the executor there must be fenced on
                    # rejoin — an ERRORED row with a live executor is the
                    # unmanaged-worker state recovery exists to eliminate
                    placement.quarantine_on_rejoin(unreachable, sid)
                self._error_service(
                    sid, "serving replica lost with its host while the "
                         "control plane was down")

        # -- rebuild serving heads for jobs with adopted replicas ----------
        for job_id in sorted(adopted_serving_jobs):
            self._check_abort()
            try:
                self._retry(
                    lambda j=job_id:
                        admin.services.adopt_inference_job(j),
                    f"serving adoption for job {job_id[:8]}")
            except RecoveryAborted:
                raise
            except Exception as e:
                logger.exception("serving adoption failed for %s", job_id)
                self._reason(f"job {job_id[:8]}: serving adoption failed "
                             f"({type(e).__name__}: {e})")

        # -- resolve half-finished rollouts (admin/rollout.py): the
        # adopted worker rows carry each replica's model_version, so a
        # rollout the dead admin left in CANARY/ROLLING is either
        # resumed-as-done (fleet already fully new-version) or rolled
        # back — never stranded mid-phase with a half-judged version
        # taking traffic
        rollouts = getattr(admin, "rollouts", None)
        if rollouts is not None:
            self._check_abort()
            try:
                rollouts.recover_on_boot()
            except RecoveryAborted:
                raise
            except Exception as e:
                logger.exception("boot-time rollout resolution failed")
                self._reason(f"rollout resolution failed "
                             f"({type(e).__name__}: {e})")

        # -- resume the drift closed loop (admin/drift.py): rows the
        # dead admin left RETRAINING/ROLLING_OUT re-attach by persisted
        # retrain id (the idempotency key); a write-ahead intent whose
        # launch fate is unknowable is adopted or parked — NEVER
        # relaunched, so a crash cannot double-spend the retrain budget
        drift = getattr(admin, "drift", None)
        if drift is not None:
            self._check_abort()
            try:
                drift.recover_on_boot()
            except RecoveryAborted:
                raise
            except Exception as e:
                logger.exception("boot-time drift resumption failed")
                self._reason(f"drift resumption failed "
                             f"({type(e).__name__}: {e})")

        # -- sweep: no job may stay non-terminal with nothing backing it ---
        self._sweep_jobs(snapshot)

    def _readopt_chip_loan(self, row: Dict[str, Any],
                           extra: Dict[str, Any]) -> None:
        """Rebuild the ChipBudgetArbiter's loan book for an adopted
        serving replica. A crashed admin's arbiter lived in memory; the
        ``borrowed_chips`` column on the worker row (written when the
        autoscaler's borrow committed) is the durable record, so an
        adopted replica that held borrowed trial chips is re-entered on
        the successor's loan book — the training plane can reclaim it
        and the fleet-health loan picture stays truthful instead of
        silently leaking the loan until the replica stops."""
        n = int(row.get("borrowed_chips") or 0)
        if n <= 0:
            return
        arbiter = getattr(self.admin, "chip_arbiter", None)
        if arbiter is None:
            return
        try:
            arbiter.note_borrow(row["id"], extra["inference_job_id"], n)
            # re-tag warm-standby loans (durable `standby` column): the
            # successor's reclaim ordering must keep draining standbys
            # FIRST, exactly like the admin that placed them would
            worker = self.db.get_inference_job_worker(row["id"])
            if worker is not None and int(worker.get("standby") or 0):
                arbiter.mark_standby(row["id"], True)
            logger.info("re-adopted a %d-chip serving loan on replica %s",
                        n, row["id"][:8])
        # lint: absorb(the loan book is advisory accounting: a rebuild failure must not fail the adoption itself)
        except Exception:
            logger.exception("chip-loan re-adoption failed for %s",
                             row["id"][:8])

    def _adopt_local_pid(self, row: Dict[str, Any],
                         extra: Dict[str, Any]) -> bool:
        """Single-host process placement: children outlive a crashed admin
        (start_new_session). Adopt a TRAIN child by its recorded pid; a
        surviving INFERENCE child is unreachable (the dead admin owned its
        shm data plane), so it is fenced instead — SIGTERM, then the
        normal lost-replica handling."""
        placement = self.admin.placement
        if hasattr(placement, "agents"):
            # hosts mode: a live pid on THIS machine may belong to a
            # co-located agent's engine (agents record child pids in the
            # same store) — adopting it here would double-manage one
            # worker from two placement engines. Agent-side services are
            # reconciled through the inventory probe instead.
            return False
        engine = placement if hasattr(placement, "adopt_pid") else None
        if engine is None:
            return False
        pid = row.get("pid")
        if not pid:
            return False
        if row["service_type"] == ServiceType.INFERENCE:
            self._fence_local_pid(row["id"], int(pid),
                                  why="its data plane died with the old "
                                      "admin")
            return False
        return bool(engine.adopt_pid(
            row["id"], row["service_type"], int(pid), extra=extra,
            chips=row.get("chips") or []))

    def _fence_local_survivor(self, row: Dict[str, Any]) -> None:
        """Adoption disabled: SIGTERM (and bounded-wait out) a surviving
        local child before its service id can be re-placed — otherwise
        the old and new executor would run concurrently under one id."""
        if hasattr(self.admin.placement, "agents"):
            return  # hosts mode: local pids may belong to agents' engines
        pid = row.get("pid")
        if not pid:
            return
        if self._fence_local_pid(row["id"], int(pid),
                                 why="RAFIKI_RECOVER_ADOPT=0",
                                 wait_s=10.0):
            self.report["fenced"] += 1
            self._reason(f"{row['id'][:8]}: fenced local child pid {pid} "
                         "(adoption disabled)")

    @staticmethod
    def _fence_local_pid(service_id: str, pid: int, why: str,
                         wait_s: float = 0.0) -> bool:
        from rafiki_tpu.placement.process import (
            _pid_is_worker,
            terminate_worker_pid,
        )

        # identity-pinned: a recycled pid belonging to some OTHER
        # service's worker must never be signalled
        if not _pid_is_worker(pid, service_id=service_id):
            return False
        logger.warning("fencing surviving child %s (pid %d): %s",
                       service_id[:8], pid, why)
        terminate_worker_pid(pid, service_id, grace_s=wait_s)
        return True

    def _restore_advisor(self, sub_train_job_id: Optional[str]) -> None:
        """An adopted train worker created its advisor session against
        the DEAD admin (advisor_id = its sub-train-job id). Rebuild the
        session in this admin's in-memory store — same id, seeded with
        the completed trials already persisted — before the worker's next
        proposal lands, or that proposal errors the very executor the
        reconcile just adopted."""
        if not sub_train_job_id:
            return
        if sub_train_job_id in self._restored_advisors:
            return
        self._restored_advisors.add(sub_train_job_id)
        try:
            sub = self.db.get_sub_train_job(sub_train_job_id)
            model = self.db.get_model(sub["model_id"]) if sub else None
            if model is None:
                return
            from rafiki_tpu.sdk.model import load_model_class

            clazz = load_model_class(model["model_file_bytes"],
                                     model["model_class"])
            store = self.admin.advisor_store
            store.create_advisor(clazz.get_knob_config(),
                                 advisor_id=sub_train_job_id)
            from rafiki_tpu.worker.faults import is_infeasible_row

            trials = self.db.get_trials_of_sub_train_job(sub_train_job_id)
            scored = [
                (t["knobs"], t["score"])
                for t in trials
                if t["status"] == TrialStatus.COMPLETED
                and t["score"] is not None
            ]
            # poison faults ride the replay too (trial fault taxonomy):
            # the rebuilt GP must also remember which regions crash,
            # not just which scored
            infeasible = [
                (t["knobs"], t["fault_kind"])
                for t in trials
                if is_infeasible_row(t)
            ]
            if (scored or infeasible) and store.replay_feedback(
                    sub_train_job_id, scored, infeasible=infeasible):
                logger.info(
                    "advisor %s rebuilt with %d replayed + %d "
                    "infeasible trials", sub_train_job_id[:8],
                    len(scored), len(infeasible))
        except Exception as e:
            logger.exception("advisor restore failed for %s",
                             sub_train_job_id)
            self._reason(f"sub {sub_train_job_id[:8]}: advisor restore "
                         f"failed ({type(e).__name__}: {e})")

    def _restart_train(self, row: Dict[str, Any], extra: Dict[str, Any],
                       n_chips: int, exclude=()) -> bool:
        """Rehome a dead host's train executor: hosts placement replays it
        through the PR-1 failover machinery (never onto an ``exclude``d —
        probe-unreachable — agent, which may still be running the old
        executor); single-host placements relaunch the worker in-process.
        Same service id either way, so the stale-RUNNING-trial resume
        continues its work."""
        placement = self.admin.placement
        if hasattr(placement, "reschedule_service"):
            try:
                return bool(placement.reschedule_service(
                    row["id"], row["service_type"], n_chips=n_chips,
                    extra=extra, exclude=exclude))
            except Exception:
                logger.exception("reschedule of %s failed", row["id"][:8])
                return False
        return self.admin.services.restart_train_worker(
            row["id"], extra["sub_train_job_id"], n_chips=n_chips)

    def _error_service(self, service_id: str, reason: str) -> None:
        """Mark a service ERRORED *through the admin's status callback*,
        so the job-level refresh side effects (train-job completion,
        serving teardown, predict-route drops) fire exactly as they would
        for a live failure."""
        self.report["errored"] += 1
        self._reason(f"{service_id[:8]}: ERRORED — {reason}")
        logger.warning("recovery: service %s ERRORED (%s)",
                       service_id[:8], reason)
        try:
            self._retry(
                lambda: self.admin._on_service_status(service_id, "ERRORED"),
                f"error service {service_id[:8]}")
        except Exception:
            logger.exception("could not error service %s", service_id)

    def _sweep_jobs(self, snapshot: Dict[str, Any]) -> None:
        """Acceptance backstop: zero rows left in a non-terminal status
        with no live (or rescheduled) service backing them. Each job's
        whole sweep runs under the bounded-retry contract — every step is
        idempotent (guarded transitions / pure reads), so a transient
        store fault re-runs the body instead of silently skipping the
        job."""
        # one indexed query for the whole live-set — not a get_service
        # round trip per worker while the doors are still 503ing
        try:
            live = self._retry(
                lambda: {s["id"] for s in self.db.get_services(
                    statuses=[ServiceStatus.STARTED,
                              ServiceStatus.DEPLOYING,
                              ServiceStatus.RUNNING])},
                "live-set scan")
        except Exception:
            logger.exception("live-set scan failed; skipping the job sweep")
            return
        for job in snapshot["train_jobs"]:
            try:
                self._retry(lambda j=job: self._sweep_one_train(j, live),
                            f"sweep train job {job['id'][:8]}")
            except RecoveryAborted:
                raise
            except Exception:
                logger.exception("train-job sweep failed for %s", job["id"])
        for job in snapshot["inference_jobs"]:
            try:
                self._retry(
                    lambda j=job: self._sweep_one_inference(j, live),
                    f"sweep inference job {job['id'][:8]}")
            except RecoveryAborted:
                raise
            except Exception:
                logger.exception("inference-job sweep failed for %s",
                                 job["id"])

    def _sweep_one_train(self, job: Dict[str, Any], live: set) -> None:
        self.admin.services.refresh_train_job_status(job["id"])
        fresh = self.db.get_train_job(job["id"])
        if fresh is None or fresh["status"] in _JOB_TERMINAL:
            return
        workers = self.db.get_workers_of_train_job(job["id"])
        if any(w["service_id"] in live for w in workers):
            return
        self.db.mark_train_job_as_errored(job["id"])
        self.report["jobs_closed"] += 1
        self._reason(f"train job {job['id'][:8]}: ERRORED — "
                     "orphaned by a dead admin (no live services)")

    def _sweep_one_inference(self, job: Dict[str, Any], live: set) -> None:
        self.admin.services.refresh_inference_job_status(job["id"])
        fresh = self.db.get_inference_job(job["id"])
        if fresh is None or fresh["status"] in (
                InferenceJobStatus.STOPPED, InferenceJobStatus.ERRORED):
            return
        workers = self.db.get_workers_of_inference_job(job["id"])
        if any(w["service_id"] in live for w in workers):
            return
        self.admin.services._teardown_serving(job["id"], errored=True)
        self.report["jobs_closed"] += 1
        self._reason(f"inference job {job['id'][:8]}: ERRORED — "
                     "orphaned by a dead admin (no live replicas)")

    def _persist_report(self) -> None:
        """Best-effort: the doctor reads the last reconcile outcome from
        disk (it has no admin process to ask).

        With control-plane HA, two admins share one LOGS_DIR across a
        failover and would clobber each other's ``recovery.json`` — the
        promoted leader's adopt report overwriting the crashed leader's
        is the exact evidence an operator needs to diff. So the report is
        written twice: the unsuffixed latest (the stable doctor/test
        path) AND an epoch-suffixed ``recovery-e<N>.json``, pruned to the
        last ``RAFIKI_RECOVERY_REPORT_KEEP``."""
        try:
            from rafiki_tpu.sdk.artifact import atomic_write_bytes

            path = report_path()
            os.makedirs(os.path.dirname(path), exist_ok=True)
            payload = {**self.report, "finished_at": time.time()}
            epoch = None
            lease = getattr(self.admin, "lease", None)
            if lease is not None:
                epoch = lease.last_epoch()
                payload["epoch"] = epoch
            blob = json.dumps(payload, indent=2).encode()
            atomic_write_bytes(path, blob)
            if epoch is not None:
                atomic_write_bytes(
                    os.path.join(os.path.dirname(path),
                                 f"recovery-e{int(epoch)}.json"), blob)
                self._prune_epoch_reports(os.path.dirname(path))
        except Exception:
            logger.exception("could not persist the recovery report")

    @staticmethod
    def _prune_epoch_reports(logs_dir: str) -> None:
        """Keep the newest RAFIKI_RECOVERY_REPORT_KEEP epoch-suffixed
        reports (sorted by epoch, which is monotonic across failovers)."""
        keep = max(int(config.RECOVERY_REPORT_KEEP), 1)
        found = []
        for name in os.listdir(logs_dir):
            m = re.fullmatch(r"recovery-e(\d+)\.json", name)
            if m:
                found.append((int(m.group(1)), name))
        for _, name in sorted(found)[:-keep]:
            try:
                os.unlink(os.path.join(logs_dir, name))
            except OSError as e:  # lint: absorb(prune is housekeeping;
                # a leftover report costs bytes, not correctness)
                logger.warning("could not prune %s: %s", name, e)
