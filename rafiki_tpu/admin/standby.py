"""Hot-standby admin (docs/failure-model.md "Control-plane HA").

A second admin process boots as a :class:`StandbyAdmin` instead of a full
:class:`Admin`: it holds no lease, runs no placement layer, and mutates
nothing. Its HTTP door (the unchanged admin/http.py, which gates on
``ha_role()``) answers login, the public root and a warm read-only
fleet-health snapshot; every other route sheds with 503 + the leader's
advertised address so clients fail over in one hop.

A watch thread polls the ``control_lease`` row. The moment the leader's
lease expires (crash, SIGSTOP past TTL, partition), the standby promotes:

1. ``LeaseManager.acquire()`` — a compare-and-set takeover that bumps the
   **epoch**. A raced sibling standby loses the CAS and simply keeps
   watching; exactly one promotes.
2. The admin factory runs — a full ``Admin`` boot under the already-held
   lease, which means the existing ``ControlPlaneRecovery`` adopt-first
   reconcile: live serving replicas are adopted (they never stopped
   answering), surviving train workers keep flowing, controllers re-arm —
   all under the new epoch.
3. The facade swaps the promoted Admin in; ``__getattr__`` delegation
   makes every admin/http.py route work against it from the next request
   on, with no server restart and no route rebuild (route lambdas resolve
   attributes at call time).

The old leader, if it comes back, is epoch-fenced everywhere: its DB
writes raise ``StaleEpochError`` at the Database chokepoint and its agent
calls are refused with a typed 412 — it can never double-place or tear
down a service the new leader owns.
"""

from __future__ import annotations

import logging
import threading
import time
from typing import Any, Callable, Dict, Optional

from rafiki_tpu import config
from rafiki_tpu.db.database import Database
from rafiki_tpu.admin.lease import (
    LeaseManager,
    ROLE_STANDBY,
    default_holder,
)
from rafiki_tpu.utils.auth import (
    UnauthorizedError,
    generate_token,
    verify_password,
)

logger = logging.getLogger(__name__)


class StandbyAdmin:
    """A delegating facade: read-only standby before promotion, a full
    Admin after. ``factory`` builds the promoted Admin and receives the
    already-acquired LeaseManager (the usual shape binds the standby's
    ``Database`` handle or makes a fresh one):

        standby = StandbyAdmin(
            db, factory=lambda lease: Admin(db=Database(), lease=lease),
            addr="127.0.0.1:3001")
    """

    def __init__(self, db: Database,
                 factory: Callable[[LeaseManager], Any],
                 addr: Optional[str] = None,
                 holder: Optional[str] = None,
                 poll_s: Optional[float] = None):
        # _lock is assigned FIRST: __getattr__ reads self._admin, and any
        # attribute touched before __init__ finishes must not recurse
        self._lock = threading.Lock()
        self._admin: Optional[Any] = None  # guarded-by: _lock
        self.db = db
        self._factory = factory
        self._lease = LeaseManager(db, holder=holder or default_holder(),
                                   addr=addr)
        p = poll_s if poll_s is not None else config.ADMIN_STANDBY_POLL_S
        self._poll_s = float(p) if p else self._lease.renew_s
        self._snapshot: Dict[str, Any] = {}  # guarded-by: _lock (warm view)
        self._stop_evt = threading.Event()
        self._thread = threading.Thread(
            target=self._watch_loop, name="admin-standby-watch", daemon=True)
        self._thread.start()

    # -- delegation --------------------------------------------------------

    def __getattr__(self, name: str) -> Any:
        # only consulted when normal lookup fails — i.e. for everything
        # the facade does not implement itself. Pre-promotion that is an
        # AttributeError (the http door's getattr-safe probes rely on it);
        # post-promotion it forwards to the real Admin.
        admin = object.__getattribute__(self, "__dict__").get("_admin")
        if admin is None:
            raise AttributeError(
                f"standby admin has no attribute {name!r} (not promoted)")
        return getattr(admin, name)

    def _promoted(self) -> Optional[Any]:
        with self._lock:
            return self._admin

    # -- the standby-served surface ----------------------------------------

    def ha_role(self) -> str:
        admin = self._promoted()
        if admin is not None:
            return admin.ha_role()
        return ROLE_STANDBY

    def leader_hint(self) -> Optional[str]:
        admin = self._promoted()
        if admin is not None:
            return admin.leader_hint()
        row = self._lease.leader_row()
        return row.get("addr") if row else None

    def ha_public(self) -> Dict[str, Any]:
        admin = self._promoted()
        if admin is not None:
            return admin.ha_public()
        return {"role": ROLE_STANDBY, "leader": self.leader_hint()}

    def recovery_status(self) -> Dict[str, Any]:
        admin = self._promoted()
        if admin is not None:
            return admin.recovery_status()
        return {"state": "ready"}

    def recovery_public(self) -> Dict[str, Any]:
        admin = self._promoted()
        if admin is not None:
            return admin.recovery_public()
        return {"state": "ready"}

    def authenticate_user(self, email: str, password: str) -> Dict[str, Any]:
        """Same contract as Admin.authenticate_user, served read-only from
        the shared store: a token minted here works against the leader
        after failover (one signing secret per deployment)."""
        admin = self._promoted()
        if admin is not None:
            return admin.authenticate_user(email, password)
        user = self.db.get_user_by_email(email)
        if user is None or not verify_password(password,
                                               user["password_hash"]):
            raise UnauthorizedError("Invalid email or password")
        if user["banned"]:
            raise UnauthorizedError("User is banned")
        token = generate_token(
            {"user_id": user["id"], "user_type": user["user_type"]})
        return {"user_id": user["id"], "user_type": user["user_type"],
                "token": token}

    def get_fleet_health(self) -> Dict[str, Any]:
        admin = self._promoted()
        if admin is not None:
            return admin.get_fleet_health()
        with self._lock:
            snapshot = dict(self._snapshot)
        return {
            "placement": None,
            "standby": True,
            "ha": {"enabled": True, **self._lease.status(),
                   "role": ROLE_STANDBY, "leader": self.leader_hint()},
            # the warm read-only view of the leader's world, refreshed
            # every poll from the shared store
            "snapshot": snapshot,
        }

    # -- the watch loop ----------------------------------------------------

    def _watch_loop(self) -> None:
        while not self._stop_evt.wait(self._poll_s):
            if self._promoted() is not None:
                return  # the promoted Admin's own lease thread takes over
            try:
                row = self.db.read_lease()
            except Exception as e:  # lint: absorb(a flaky store must not
                # kill the watcher; the next poll retries)
                logger.warning("standby lease watch failed: %s", e)
                continue
            expired = row is None or row["expires_at"] <= time.time()
            if not expired:
                self._refresh_snapshot()
                continue
            try:
                self._promote()
            except Exception:
                # a raced CAS loss is handled inside _promote; anything
                # else (factory failure mid-boot) is logged and retried —
                # a standby that dies on one failed promotion attempt
                # would leave the fleet leaderless for good
                logger.exception("standby promotion attempt failed; "
                                 "will retry")
            if self._promoted() is not None:
                return

    def _refresh_snapshot(self) -> None:
        """The warm read-only view standby fleet-health serves: cheap
        store-derived counts, never placement state (there is none)."""
        try:
            snap: Dict[str, Any] = {
                "inference_jobs_running": len(
                    self.db.get_inference_jobs_by_statuses(["RUNNING"])),
                "refreshed_at": time.time(),
            }
        except Exception as e:  # lint: absorb(snapshot is best-effort
            # observability; store faults surface in the next poll)
            logger.warning("standby snapshot refresh failed: %s", e)
            return
        with self._lock:
            self._snapshot = snap

    def _promote(self) -> None:
        """Lease takeover + full Admin boot. The CAS in acquire() makes
        this race-safe: of N standbys watching one expired lease, exactly
        one wins the epoch bump; losers return to watching."""
        if not self._lease.acquire(block=False):
            logger.info("standby %s lost the promotion race; resuming "
                        "watch", self._lease.holder)
            return
        epoch = self._lease.last_epoch()
        logger.warning("standby %s promoting to leader at epoch %s",
                       self._lease.holder, epoch)
        # the factory runs the full Admin boot — including the adopt-first
        # ControlPlaneRecovery reconcile — under the already-held lease
        admin = self._factory(self._lease)
        with self._lock:
            self._admin = admin
        logger.warning("standby promotion complete: leader at epoch %s",
                       epoch)

    # -- lifecycle ---------------------------------------------------------

    def wait_promoted(self, timeout_s: float) -> bool:
        """Test/ops helper: block until this standby has promoted."""
        deadline = time.monotonic() + timeout_s
        while time.monotonic() < deadline:
            if self._promoted() is not None:
                return True
            time.sleep(0.05)
        return self._promoted() is not None

    def shutdown(self) -> None:
        self._stop_evt.set()
        self._thread.join(timeout=5.0)
        admin = self._promoted()
        if admin is not None:
            admin.shutdown()
        else:
            # never held the lease; release=False keeps the row untouched
            self._lease.stop(release=False)
