"""Warm standby replica pool (docs/failure-model.md "Cold-start faults").

`RAFIKI_AUTOSCALE_WARM_POOL=K` (off by default, like every policy) keeps
K pre-loaded, pre-warmed STANDBY replicas per RUNNING inference job:
placed like any scale-up replica (chips held through the
ChipBudgetArbiter's borrow book — the training floor still outranks
them, and training's reclaim drains standbys FIRST), fully booted and
jit-compiled, but never handed to the predictor. Scale-up and
failed-replica replacement then become an ``add_worker`` route (~ms)
instead of a deploy: the MTTR cliff every recovery path used to end at
(ROADMAP item 3, the r5 cold-compile collapse) turns into routing.

The maintenance loop, each ``RAFIKI_AUTOSCALE_WARM_POOL_INTERVAL_S``:

- **top-up** — place standbys until each RUNNING job holds K (bounded
  retries: ``RAFIKI_AUTOSCALE_WARM_RETRY_MAX`` consecutive failures
  park the job's pool DEGRADED for
  ``RAFIKI_AUTOSCALE_WARM_RETRY_COOLDOWN_S`` instead of wedging the
  loop against a placement that cannot succeed);
- **retire stale versions** — a standby whose model_version fell behind
  what its group serves (a rollout advanced past it) is destroyed and
  replaced next tick, so a promotion can never resurrect an old version;
- **replace on failure** — Admin._on_service_status calls
  :meth:`on_replica_errored` when a routable serving replica dies: a
  standby is promoted immediately (zero-deploy replacement), and the
  next tick replenishes the pool.

Recovery integration: standbys are ordinary services with a durable
``standby`` worker-row column, so the adopt-or-fence pass treats them
like any replica — adopted (or swept) on boot, kept out of the routable
set (admin/services.py adopt_inference_job), their chip loans re-entered
standby-tagged (admin/recovery.py _readopt_chip_loan).

Reference analogue: none — the reference Rafiki had no warm capacity
concept; its MTTR was container boot plus framework cold start.
"""

from __future__ import annotations

import collections
import logging
import threading
import time
from typing import Any, Deque, Dict, List, Optional

from rafiki_tpu import config
from rafiki_tpu.constants import InferenceJobStatus

logger = logging.getLogger(__name__)


class WarmPool:
    """One per Admin. The loop thread only runs when
    ``RAFIKI_AUTOSCALE_WARM_POOL`` > 0 (or :meth:`start` is called
    explicitly); a stopped instance still answers :meth:`report` so
    /fleet/health always has the section."""

    def __init__(self, admin) -> None:
        self._admin = admin
        self._services = admin.services
        self._db = admin.db
        self._arbiter = getattr(admin, "chip_arbiter", None)
        self._closed = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._lock = threading.Lock()
        # per-job pool state: consecutive placement failures + the
        # DEGRADED cooldown deadline
        # {job_id: {"failures": int, "degraded_until": float,
        #           "last_error": str}}
        self._jobs: Dict[str, Dict[str, Any]] = {}  # guarded-by: _lock
        #: bounded event log, newest last (fleet-health "warm_pool")
        self.events: Deque[Dict[str, Any]] = (  # guarded-by: _lock
            collections.deque(maxlen=100))
        from rafiki_tpu.utils.metrics import REGISTRY

        self._g_standbys = REGISTRY.gauge(
            "rafiki_warm_pool_standbys",
            "warm standby replicas currently held, per job", ("job",))
        self._m_ticks = REGISTRY.counter(
            "rafiki_warm_pool_ticks_total", "warm-pool maintenance ticks")

    # -- lifecycle ----------------------------------------------------------

    @property
    def running(self) -> bool:
        return self._thread is not None and self._thread.is_alive()

    def start(self) -> "WarmPool":
        if self.running:
            return self
        self._closed.clear()
        self._thread = threading.Thread(
            target=self._loop, name="warm-pool", daemon=True)
        self._thread.start()
        logger.info(
            "warm pool loop started (K=%d, interval %.1fs)",
            int(config.AUTOSCALE_WARM_POOL),
            float(config.AUTOSCALE_WARM_POOL_INTERVAL_S))
        return self

    def stop(self) -> None:
        self._closed.set()
        t = self._thread
        if t is not None:
            # a tick may sit inside a standby's deploy wait
            t.join(timeout=float(config.SERVICE_DEPLOY_TIMEOUT_S) + 10)
        self._thread = None

    def _loop(self) -> None:
        while not self._closed.wait(
                float(config.AUTOSCALE_WARM_POOL_INTERVAL_S)):
            try:
                self.tick()
            except Exception:
                logger.exception("warm pool tick failed")

    # -- the maintenance loop -----------------------------------------------

    def tick(self) -> List[Dict[str, Any]]:
        """One maintenance pass over every RUNNING inference job. Public
        and synchronous so tests (and an operator REPL) can drive the
        pool deterministically without the thread."""
        self._m_ticks.inc()
        want = max(int(config.AUTOSCALE_WARM_POOL), 0)
        actions: List[Dict[str, Any]] = []
        jobs = self._db.get_inference_jobs_by_statuses(
            [InferenceJobStatus.RUNNING])
        seen = set()
        for job in jobs:
            job_id = job["id"]
            seen.add(job_id)
            try:
                actions.extend(self._tick_job(job_id, want))
            except Exception:
                logger.exception("warm pool tick for job %s failed",
                                 job_id[:8])
        # drop state (and the gauge series) for jobs that ended
        with self._lock:
            for job_id in [j for j in self._jobs if j not in seen]:
                del self._jobs[job_id]
                self._g_standbys.labels(job_id).set(0)
        return actions

    def _tick_job(self, job_id: str, want: int) -> List[Dict[str, Any]]:
        actions: List[Dict[str, Any]] = []
        standbys = self._services.standby_workers(job_id)
        # -- retire stale versions: a standby a rollout advanced past
        # must never be promotable (admin/services.py promote_standby
        # also guards, but a retired standby frees its chips NOW)
        cur: Dict[str, int] = {}
        for w in self._services.live_inference_workers(job_id):
            cur[w["group"]] = max(cur.get(w["group"], 0),
                                  w["model_version"])
        fresh = []
        for w in standbys:
            if w["model_version"] < cur.get(w["group"], 0):
                self._services.drop_standby(w["service_id"])
                actions.append(self._event(
                    job_id, "retire_stale",
                    service_id=w["service_id"],
                    version=w["model_version"],
                    serving_version=cur.get(w["group"], 0)))
            else:
                fresh.append(w)
        standbys = fresh
        self._g_standbys.labels(job_id).set(len(standbys))
        # -- shrink when K was lowered
        while len(standbys) > want:
            w = standbys.pop()
            self._services.drop_standby(w["service_id"])
            actions.append(self._event(job_id, "shrink",
                                       service_id=w["service_id"]))
        # -- top-up toward K, bounded-retry + DEGRADED cooldown
        state = self._state(job_id)
        now = time.monotonic()
        if state["degraded_until"] > now:
            return actions
        retry_max = max(int(config.AUTOSCALE_WARM_RETRY_MAX), 1)
        while len(standbys) < want:
            try:
                sid = self._services.create_standby_replica(job_id)
            except Exception as e:
                with self._lock:
                    state["failures"] += 1
                    state["last_error"] = f"{type(e).__name__}: {e}"
                    failures = state["failures"]
                logger.warning("warm pool: placing a standby for job %s "
                               "failed (%d consecutive): %s", job_id[:8],
                               failures, e)
                if failures >= retry_max:
                    cooldown = float(
                        config.AUTOSCALE_WARM_RETRY_COOLDOWN_S)
                    with self._lock:
                        state["degraded_until"] = now + cooldown
                        state["failures"] = 0
                    actions.append(self._event(
                        job_id, "degraded", error=str(e),
                        cooldown_s=cooldown))
                else:
                    actions.append(self._event(job_id, "place_failed",
                                               error=str(e)))
                break
            with self._lock:
                state["failures"] = 0
                state["last_error"] = None
            standbys.append({"service_id": sid})
            self._g_standbys.labels(job_id).set(len(standbys))
            actions.append(self._event(job_id, "place",
                                       service_id=sid))
        return actions

    # -- failure replacement (Admin._on_service_status) ----------------------

    def on_replica_errored(self, service_id: str,
                           inference_job_id: str) -> Optional[str]:
        """A routable serving replica died: promote a standby in its
        group NOW (an add_worker route — the zero-deploy replacement),
        leaving the next tick to replenish the pool. Returns the
        promoted service id, or None (empty pool / the dead replica was
        itself a standby)."""
        try:
            row = self._db.get_inference_job_worker(service_id)
        # lint: absorb(an unreadable worker row only skips the fast-path replacement; the autoscaler/operator path still works)
        except Exception:
            return None
        if row is None or int(row.get("standby") or 0):
            return None
        promoted = self._services.promote_standby(inference_job_id)
        if promoted is not None:
            self._event(inference_job_id, "replace",
                        failed=service_id, promoted=promoted)
            logger.info(
                "warm pool: replaced failed replica %s of job %s with "
                "standby %s", service_id[:8], inference_job_id[:8],
                promoted[:8])
        return promoted

    # -- reporting ----------------------------------------------------------

    def _state(self, job_id: str) -> Dict[str, Any]:
        with self._lock:
            return self._jobs.setdefault(
                job_id, {"failures": 0, "degraded_until": 0.0,
                         "last_error": None})

    def _event(self, job_id: str, action: str, **detail: Any,
               ) -> Dict[str, Any]:
        ev = {"ts": time.time(), "job_id": job_id, "action": action,
              **detail}
        with self._lock:
            self.events.append(ev)
        return ev

    def report(self) -> Dict[str, Any]:
        """The /fleet/health "warm_pool" section."""
        now = time.monotonic()
        with self._lock:
            jobs = {
                job_id: {
                    "failures": s["failures"],
                    "degraded": s["degraded_until"] > now,
                    "last_error": s["last_error"],
                }
                for job_id, s in self._jobs.items()
            }
            events = list(self.events)[-20:]
        out: Dict[str, Any] = {
            "enabled": int(config.AUTOSCALE_WARM_POOL) > 0,
            "running": self.running,
            "target_per_job": int(config.AUTOSCALE_WARM_POOL),
            "jobs": jobs,
            "events": events,
        }
        if self._arbiter is not None and hasattr(self._arbiter,
                                                 "loan_split"):
            out["loans"] = self._arbiter.loan_split()
        return out
