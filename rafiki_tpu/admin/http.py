"""REST API over Admin (reference rafiki/admin/app.py:13-397).

Same resource model and JWT-style auth with per-route allowed user types
(reference utils/auth.py:28-45). Built on the stdlib threading HTTP server —
no Flask dependency — as a thin shell over the Admin library; every route
body is one Admin call.

Model upload: JSON with the template file base64-encoded (the reference used
multipart; base64-in-JSON keeps the stdlib server simple and the client SDK
hides the encoding either way).
"""

from __future__ import annotations

import base64
import json
import logging
import os
import re
import threading
import traceback
from http.server import BaseHTTPRequestHandler
from typing import Any, Callable, Dict, List, Optional, Tuple
from urllib.parse import parse_qs, urlparse

from rafiki_tpu import config
from rafiki_tpu.admin.admin import Admin, InvalidRequestError
from rafiki_tpu.admin.rollout import RolloutInFlightError
from rafiki_tpu.cache.queue import FrameTooLargeError, QueueFullError
from rafiki_tpu.constants import UserType
from rafiki_tpu.db.database import StaleEpochError
from rafiki_tpu.placement.hosts import StaleAdminEpochError
from rafiki_tpu.placement.manager import InsufficientChipsError
from rafiki_tpu.predictor.admission import (
    DeadlineUnmeetableError,
    ServerOverloadedError,
    retry_after_headers,
)
from rafiki_tpu.sdk.artifact import ArtifactCorruptError
from rafiki_tpu.sdk.model import InvalidModelClassError
from rafiki_tpu.utils.auth import UnauthorizedError, auth_check, decode_token
from rafiki_tpu.utils.reqfields import (
    LowLatencyHandler,
    SeveringHTTPServer,
    read_bounded_body,
)

logger = logging.getLogger(__name__)

_ANY = None  # any authenticated user
_ADMINS = [UserType.ADMIN, UserType.SUPERADMIN]
_MODEL_DEVS = [UserType.MODEL_DEVELOPER] + _ADMINS
_APP_DEVS = [UserType.APP_DEVELOPER] + _ADMINS

Route = Tuple[str, re.Pattern, Optional[List[str]], Callable]


def _field(body: Dict[str, Any], name: str) -> Any:
    """A required body field. Raised as InvalidRequestError (→ 400) at the
    route boundary so the dispatch loop never has to catch KeyError — a
    KeyError from inside Admin is then a genuine 500, not a masked 400."""
    try:
        return body[name]
    except (KeyError, TypeError):
        raise InvalidRequestError(f"missing body field '{name}'")


def _num_field(body: Dict[str, Any], name: str, cast, default=None):
    """A numeric body field coerced with ``cast`` (int/float); malformed
    values are client errors. ``default=None`` makes the field required."""
    if name not in body:
        if default is None:
            raise InvalidRequestError(f"missing body field '{name}'")
        return default
    try:
        return cast(body[name])
    except (ValueError, TypeError) as e:
        raise InvalidRequestError(
            f"field '{name}' must be {cast.__name__}: {e}")


def _b64_field(body: Dict[str, Any], name: str) -> bytes:
    """Decode a base64 body field; malformed input is a client error, not a
    server bug — keep broad except clauses out of the dispatch loop."""
    try:
        return base64.b64decode(_field(body, name))
    except (ValueError, TypeError) as e:
        raise InvalidRequestError(f"field '{name}' is not valid base64: {e}")


def _list_field(body: Dict[str, Any], name: str) -> list:
    """A required body field that must be a JSON array; anything else is
    a client error (the fuzz contract: malformed bodies answer 4xx, never
    a 500 from iterating an int)."""
    value = _field(body, name)
    if not isinstance(value, list):
        raise InvalidRequestError(
            f"field '{name}' must be a list, got {type(value).__name__}")
    return value


# Door cap on batched advisor proposals: each draw is a GP fit + EI
# optimization under the session lock, so an unbounded client-supplied k
# could pin the advisor (and starve every worker sharing it) for hours.
# Workers clamp far lower (RAFIKI_TRIAL_VMAP_K, PopulationSpec
# max_members); this bound is the trust boundary's backstop.
PROPOSE_BATCH_MAX = 64


def _knob_config_field(body: Dict[str, Any]):
    """Deserialize a client-supplied knob_config; any malformed shape or
    unknown knob type is a client error, validated here at the route
    boundary."""
    from rafiki_tpu.sdk.knob import deserialize_knob_config

    try:
        return deserialize_knob_config(_field(body, "knob_config"))
    except (ValueError, TypeError, KeyError, AttributeError) as e:
        raise InvalidRequestError(f"invalid knob_config: {e}")


def _int_param(query: Dict[str, str], name: str, default: int) -> int:
    try:
        return int(query.get(name, default))
    except (ValueError, TypeError) as e:
        raise InvalidRequestError(f"query param '{name}' must be an int: {e}")


class AdminServer:
    """HTTP façade; start() binds and serves on a daemon thread."""

    def __init__(self, admin: Admin, host: str = "127.0.0.1", port: int = 0):
        self.admin = admin
        self.host = host
        self.port = port
        self._httpd: Optional[SeveringHTTPServer] = None
        self._thread: Optional[threading.Thread] = None
        self.routes: List[Route] = self._build_routes()

    # -- lifecycle ---------------------------------------------------------

    def start(self) -> "AdminServer":
        server = self

        class Handler(LowLatencyHandler):
            # HTTP/1.1: keep-alive, so a client session reuses one
            # connection (and one server thread) across requests instead of
            # paying connect + thread-spawn per call. Safe because every
            # response path sends Content-Length. The idle timeout reaps
            # the thread of a client that died without closing (SIGKILL'd
            # worker) — otherwise dead-connection threads pile up forever.
            protocol_version = "HTTP/1.1"
            timeout = 300

            def do_GET(self):
                server._dispatch(self, "GET")

            def do_POST(self):
                server._dispatch(self, "POST")

            def do_DELETE(self):
                server._dispatch(self, "DELETE")

        self._httpd = SeveringHTTPServer((self.host, self.port), Handler)
        self.port = self._httpd.server_address[1]
        # worker *processes* coordinate HPO + events through this API;
        # tell the placement layer where it lives (placement/process.py)
        # getattr-safe: a hot standby (admin/standby.py) has no placement
        # layer until it promotes; its door serves hints + login only
        placement = getattr(self.admin, "placement", None)
        if placement is not None and hasattr(placement, "admin_addr"):
            placement.admin_addr = (self.host, self.port)
        self._thread = threading.Thread(
            target=self._httpd.serve_forever, daemon=True
        )
        self._thread.start()
        return self

    def stop(self) -> None:
        if self._httpd:
            self._httpd.shutdown()
            self._httpd.server_close()
            # sever established keep-alive connections too: a stopped
            # door must go dark like a killed process, or HA drills keep
            # being served by the "dead" leader's handler threads
            self._httpd.sever()

    # -- routing -----------------------------------------------------------

    def _build_routes(self) -> List[Route]:
        A = self.admin

        def r(method: str, pattern: str, allowed, fn) -> Route:
            return (method, re.compile(f"^{pattern}$"), allowed, fn)

        return [
            # recovery STATE rides the public root so any client can wait
            # out a restarting admin without credentials (the full report
            # — ids, agent addresses, failure reasons — needs the
            # admin-rights /fleet/health)
            r("GET", "/", "public", lambda au, m, b, q: {
                "name": "rafiki_tpu admin", "status": "ok",
                "recovery": A.recovery_public(),
                # control-plane HA role + leader hint (public on purpose:
                # failover clients walk addresses pre-auth)
                "ha": getattr(A, "ha_public", lambda: {"role": "leader"})()}),
            r("POST", "/tokens", "public", lambda au, m, b, q: A.authenticate_user(
                _field(b, "email"), _field(b, "password"))),
            # users
            r("POST", "/users", _ADMINS, lambda au, m, b, q: A.create_user(
                _field(b, "email"), _field(b, "password"), _field(b, "user_type"))),
            r("GET", "/users", _ADMINS, lambda au, m, b, q: A.get_users()),
            r("DELETE", "/users", _ADMINS, lambda au, m, b, q: A.ban_user(
                _field(b, "email"))),
            # models
            r("POST", "/models", _MODEL_DEVS, lambda au, m, b, q: A.create_model(
                au["user_id"], _field(b, "name"), _field(b, "task"),
                _b64_field(b, "model_file_base64"), _field(b, "model_class"),
                b.get("dependencies"), b.get("access_right", "PRIVATE"))),
            # static-analysis dry run (analysis/template.py): the full
            # finding report, no model row created — the pre-upload loop
            # (Client.verify_model / python -m rafiki_tpu.analysis)
            r("POST", "/models/verify", _MODEL_DEVS,
                lambda au, m, b, q: A.verify_model(
                    _b64_field(b, "model_file_base64"),
                    _field(b, "model_class"), b.get("dependencies"))),
            r("GET", "/models", _ANY, lambda au, m, b, q: A.get_models(
                au["user_id"], q.get("task"))),
            r("GET", r"/models/(?P<name>[^/]+)", _ANY, lambda au, m, b, q:
                A.get_model(au["user_id"], m["name"], q.get("owner_id"))),
            r("GET", r"/models/(?P<name>[^/]+)/file", _ANY, lambda au, m, b, q:
                {"model_file_base64": base64.b64encode(A.get_model_file(
                    au["user_id"], m["name"], q.get("owner_id"))).decode()}),
            r("DELETE", r"/models/(?P<name>[^/]+)", _MODEL_DEVS,
                lambda au, m, b, q: A.delete_model(au["user_id"], m["name"]) or {}),
            # train jobs
            r("POST", "/train_jobs", _APP_DEVS, lambda au, m, b, q:
                A.create_train_job(
                    au["user_id"], _field(b, "app"), _field(b, "task"), _field(b, "train_dataset_uri"),
                    _field(b, "test_dataset_uri"), b.get("budget"), b.get("models"))),
            r("GET", "/train_jobs", _ANY, lambda au, m, b, q:
                A.get_train_jobs_of_user(au["user_id"])),
            r("GET", r"/train_jobs/(?P<app>[^/]+)", _ANY, lambda au, m, b, q:
                A.get_train_jobs_of_app(au["user_id"], m["app"])),
            r("GET", r"/train_jobs/(?P<app>[^/]+)/(?P<v>-?\d+)", _ANY,
                lambda au, m, b, q: A.get_train_job(
                    au["user_id"], m["app"], int(m["v"]))),
            r("POST", r"/train_jobs/(?P<app>[^/]+)/(?P<v>-?\d+)/stop", _APP_DEVS,
                lambda au, m, b, q: A.stop_train_job(
                    au["user_id"], m["app"], int(m["v"]))),
            r("GET", r"/train_jobs/(?P<app>[^/]+)/(?P<v>-?\d+)/trials", _ANY,
                lambda au, m, b, q: A.get_trials_of_train_job(
                    au["user_id"], m["app"], int(m["v"]))),
            r("GET", r"/train_jobs/(?P<app>[^/]+)/(?P<v>-?\d+)/best_trials",
                _ANY, lambda au, m, b, q: A.get_best_trials_of_train_job(
                    au["user_id"], m["app"], int(m["v"]),
                    _int_param(q, "max_count", 2))),
            # trials
            r("GET", r"/trials/(?P<tid>[^/]+)/logs", _ANY, lambda au, m, b, q:
                A.get_trial_logs(m["tid"])),
            r("GET", r"/trials/(?P<tid>[^/]+)/trace", _ANY, lambda au, m, b, q:
                A.get_trial_trace(m["tid"])),
            r("GET", r"/trials/(?P<tid>[^/]+)/parameters", _ANY,
                lambda au, m, b, q: {"params_base64": base64.b64encode(
                    A.get_trial_params(m["tid"])).decode()}),
            r("GET", r"/trials/(?P<tid>[^/]+)", _ANY, lambda au, m, b, q:
                A.get_trial(m["tid"])),
            # inference jobs
            r("POST", "/inference_jobs", _APP_DEVS, lambda au, m, b, q:
                A.create_inference_job(
                    au["user_id"], _field(b, "app"), b.get("app_version", -1),
                    budget=b.get("budget"))),
            r("GET", r"/inference_jobs/(?P<app>[^/]+)/(?P<v>-?\d+)", _ANY,
                lambda au, m, b, q: A.get_inference_job(
                    au["user_id"], m["app"], int(m["v"]))),
            r("GET", r"/inference_jobs/(?P<app>[^/]+)/(?P<v>-?\d+)/stats",
                _ANY, lambda au, m, b, q: A.get_inference_job_stats(
                    au["user_id"], m["app"], int(m["v"]))),
            r("POST", r"/inference_jobs/(?P<app>[^/]+)/(?P<v>-?\d+)/stop",
                _APP_DEVS, lambda au, m, b, q: A.stop_inference_job(
                    au["user_id"], m["app"], int(m["v"]))),
            # elastic serving: add / gracefully drain replicas at runtime
            # (admin/autoscaler.py drives the same primitive)
            r("POST", r"/inference_jobs/(?P<app>[^/]+)/(?P<v>-?\d+)/scale",
                _APP_DEVS, lambda au, m, b, q: A.scale_inference_job(
                    au["user_id"], m["app"], int(m["v"]),
                    delta=_num_field(b, "delta", int))),
            # safe live rollouts (admin/rollout.py): update the RUNNING
            # inference job to a new trial in place — canary, SLO judge,
            # rolling replace, automatic rollback. A second update while
            # one is in flight answers a typed 409.
            r("POST", r"/inference_jobs/(?P<app>[^/]+)/(?P<v>-?\d+)/update",
                _APP_DEVS, lambda au, m, b, q: A.update_inference_job(
                    au["user_id"], m["app"], int(m["v"]),
                    trial_id=_field(b, "trial_id"),
                    canary_fraction=(
                        _num_field(b, "canary_fraction", float, -1.0)
                        if "canary_fraction" in b else None),
                    batch=(_num_field(b, "batch", int, 1)
                           if "batch" in b else None))),
            r("GET", r"/inference_jobs/(?P<app>[^/]+)/(?P<v>-?\d+)/rollout",
                _ANY, lambda au, m, b, q: A.get_rollout_status(
                    au["user_id"], m["app"], int(m["v"]))),
            r("POST",
                r"/inference_jobs/(?P<app>[^/]+)/(?P<v>-?\d+)/rollout/abort",
                _APP_DEVS, lambda au, m, b, q: A.abort_rollout(
                    au["user_id"], m["app"], int(m["v"]))),
            r("POST",
                r"/inference_jobs/(?P<app>[^/]+)/(?P<v>-?\d+)/rollout/ack",
                _APP_DEVS, lambda au, m, b, q: A.ack_rollout(
                    au["user_id"], m["app"], int(m["v"]))),
            # drift closed loop (admin/drift.py): the job's loop state +
            # live signals; ack re-arms a parked loop / clears a flap
            r("GET", r"/inference_jobs/(?P<app>[^/]+)/(?P<v>-?\d+)/drift",
                _ANY, lambda au, m, b, q: A.get_drift_status(
                    au["user_id"], m["app"], int(m["v"]))),
            r("POST",
                r"/inference_jobs/(?P<app>[^/]+)/(?P<v>-?\d+)/drift/ack",
                _APP_DEVS, lambda au, m, b, q: A.ack_drift(
                    au["user_id"], m["app"], int(m["v"]))),
            # serving (the reference exposed this on a separate predictor app,
            # reference predictor/app.py:23-31)
            r("POST", r"/predict/(?P<app>[^/]+)", _ANY, lambda au, m, b, q:
                {"predictions": A.predict(
                    au["user_id"], m["app"], _field(b, "queries"),
                    b.get("app_version", -1))}),
            # advisor sessions (reference advisor/app.py:17-50)
            r("POST", "/advisors", _ANY, lambda au, m, b, q: {
                "advisor_id": A.advisor_store.create_advisor(
                    _knob_config_field(b),
                    advisor_id=b.get("advisor_id"))}),
            r("POST", r"/advisors/(?P<aid>[^/]+)/propose", _ANY,
                lambda au, m, b, q: {"knobs": A.advisor_store.propose(m["aid"])}),
            # batched proposals for vectorized trial execution: K knob
            # assignments in one call (the GP spreads them via its
            # pending-point fantasies); old clients keep using /propose
            r("POST", r"/advisors/(?P<aid>[^/]+)/propose_batch", _ANY,
                lambda au, m, b, q: {
                    "knobs_list": A.advisor_store.propose_batch(
                        m["aid"], max(1, min(_num_field(b, "k", int, 1),
                                             PROPOSE_BATCH_MAX)))}),
            r("POST", r"/advisors/(?P<aid>[^/]+)/feedback", _ANY,
                lambda au, m, b, q: {"knobs": A.advisor_store.feedback(
                    m["aid"], _field(b, "knobs"), _field(b, "score"))}),
            # the batch's return leg: K (knobs, score) observations,
            # applied member-by-member (each retires its own fantasy)
            r("POST", r"/advisors/(?P<aid>[^/]+)/feedback_batch", _ANY,
                lambda au, m, b, q: {
                    "count": A.advisor_store.feedback_batch(
                        m["aid"],
                        [(_field(i, "knobs"), _field(i, "score"))
                         for i in _list_field(b, "items")])}),
            # scoreless-failure signal (trial fault taxonomy): the GP
            # steers away from the region; trial_id lets the session's
            # ASHA scheduler forget the dead trial's rung records
            r("POST", r"/advisors/(?P<aid>[^/]+)/infeasible", _ANY,
                lambda au, m, b, q: {
                    "infeasible": A.advisor_store.feedback_infeasible(
                        m["aid"], _field(b, "knobs"),
                        kind=b.get("kind", "USER"),
                        trial_id=b.get("trial_id"))}),
            r("POST", r"/advisors/(?P<aid>[^/]+)/replay", _ANY,
                lambda au, m, b, q: {"replayed": A.advisor_store.replay_feedback(
                    m["aid"],
                    [(_field(i, "knobs"), _field(i, "score"))
                     for i in _list_field(b, "items")],
                    infeasible=[
                        (_field(i, "knobs"), i.get("kind", "USER"))
                        for i in b.get("infeasible") or []])}),
            # ASHA rung report (early stopping; advisor/asha.py)
            r("POST", r"/advisors/(?P<aid>[^/]+)/report_rung", _ANY,
                lambda au, m, b, q: {"keep": A.advisor_store.report_rung(
                    m["aid"], _field(b, "trial_id"), _num_field(b, "resource", int),
                    _num_field(b, "value", float),
                    min_resource=_num_field(b, "min_resource", int, 1),
                    eta=_num_field(b, "eta", int, 3),
                    mode=b.get("mode", "min"))}),
            r("DELETE", r"/advisors/(?P<aid>[^/]+)", _ANY, lambda au, m, b, q:
                A.advisor_store.delete_advisor(m["aid"]) or {}),
            # admin actions (reference scripts/stop_all_jobs.py via client)
            r("POST", "/actions/stop_all_jobs", _ADMINS,
                lambda au, m, b, q: A.stop_all_jobs() or {}),
            # fleet health: per-agent heartbeat + circuit breaker state
            # (placement/hosts.py monitor; docs/failure-model.md)
            r("GET", "/fleet/health", _ADMINS,
                lambda au, m, b, q: A.get_fleet_health()),
            # internal events (reference admin/app.py:360). Workers
            # authenticate as superadmin (as the reference's did, reference
            # worker/train.py:261-263); plain users must not be able to stop
            # other tenants' services through this.
            r("POST", r"/event/(?P<name>[^/]+)", _ADMINS, lambda au, m, b, q:
                A.handle_event(m["name"], b) or {}),
        ]

    # -- static web admin --------------------------------------------------

    _WEB_DIR = os.path.join(os.path.dirname(os.path.abspath(__file__)), "web")

    def _serve_web(self, handler: BaseHTTPRequestHandler) -> None:
        """Serve the single-file dashboard SPA (the analogue of the
        reference's React/Express web admin, reference web/app.js:12-17 —
        here one static HTML file against the same-origin REST API)."""
        try:
            with open(os.path.join(self._WEB_DIR, "index.html"), "rb") as f:
                data = f.read()
        except OSError:
            self._respond(handler, 404, {"error": "web UI assets missing"})
            return
        handler.send_response(200)
        handler.send_header("Content-Type", "text/html; charset=utf-8")
        handler.send_header("Content-Length", str(len(data)))
        handler.end_headers()
        handler.wfile.write(data)

    def _dispatch(self, handler: BaseHTTPRequestHandler, method: str) -> None:
        try:
            parsed = urlparse(handler.path)
            path = parsed.path.rstrip("/") or "/"
            if method == "GET" and path == "/web":
                self._serve_web(handler)
                return
            if method == "GET" and path == "/metrics":
                # Prometheus text exposition (utils/metrics.py — one
                # rendering shared with the agent and predictor doors).
                # Public like the reference scraper contract, and exempt
                # from the recovery gate: a reconciling admin's metrics
                # are exactly what an operator wants to watch.
                from rafiki_tpu.utils.metrics import serve_http

                serve_http(handler, parsed.query)
                return
            # boot gate: while the control plane reconciles a crashed
            # predecessor's state (admin/recovery.py), every route that
            # could read or mutate half-reconciled state sheds with 503 +
            # Retry-After. Allowed through: the public root (carries the
            # recovery state), login, the fleet-health view, worker
            # events (agents keep forwarding statuses DURING recovery),
            # and the advisor routes — surviving train workers the
            # reconcile is adopting keep proposing/reporting mid-trial,
            # and the advisor store is fresh in-memory state, not part of
            # what is being reconciled.
            # the body is read BEFORE any gate can answer: an early 503
            # that leaves the body unread desyncs HTTP/1.1 keep-alive
            # framing — the next request on the pooled connection parses
            # the leftover bytes as its request line (a failover client
            # walking back to this door then sees a bogus 400)
            body: Dict[str, Any] = {}
            raw, berr = read_bounded_body(
                handler, config.ADMIN_MAX_BODY_MB, fallback_mb=256.0)
            if berr:
                # this door's error channel is InvalidRequestError (400)
                raise InvalidRequestError(f"{berr[1]} (ADMIN_MAX_BODY_MB)")
            # standby gate (control-plane HA, admin/standby.py): a hot
            # standby answers login, the public root and the fleet-health
            # snapshot read-only; everything else sheds with 503 + the
            # leader's address so clients fail over in one hop instead of
            # polling. Checked BEFORE the recovery gate — a standby has no
            # recovery state to consult until it promotes.
            role = getattr(self.admin, "ha_role", None)
            role = role() if callable(role) else "leader"
            if role == "standby" and not (
                    path == "/" or path == "/tokens"
                    or path == "/fleet/health"):
                self._respond(
                    handler, 503,
                    {"error": "admin is a hot standby; mutations go to "
                              "the leader",
                     "standby": True,
                     "leader": self.admin.leader_hint()},
                    headers={"Retry-After": "1"})
                return
            state = self.admin.recovery_status()
            if state.get("state") == "recovering" and not (
                    path == "/" or path == "/tokens"
                    or path == "/fleet/health"
                    or path.startswith("/event/")
                    or path.startswith("/advisors")):
                self._respond(
                    handler, 503,
                    {"error": "admin is recovering (boot reconciliation "
                              "in progress); retry shortly",
                     # state only: most gated routes are pre-auth, and
                     # the full report carries internal ids/addresses
                     "recovery": self.admin.recovery_public()},
                    headers={"Retry-After": "1"})
                return
            query = {k: v[0] for k, v in parse_qs(parsed.query).items()}
            try:
                if raw:
                    body = json.loads(raw or b"{}")
            except (ValueError, UnicodeDecodeError) as e:
                # malformed JSON or non-UTF-8 bytes (body fully read)
                raise InvalidRequestError(f"malformed request body: {e}")
            if raw and not isinstance(body, dict):
                raise InvalidRequestError("request body must be a JSON object")

            for m, pattern, allowed, fn in self.routes:
                if m != method:
                    continue
                match = pattern.match(path)
                if not match:
                    continue
                if allowed == "public":
                    auth: Dict[str, Any] = {}
                else:
                    token = (handler.headers.get("Authorization") or "").removeprefix(
                        "Bearer "
                    )
                    auth = decode_token(token)
                    if allowed is not _ANY:
                        auth_check(auth, allowed)
                result = fn(auth, match.groupdict(), body, query)
                self._respond(handler, 200, {"data": result})
                return
            self._respond(handler, 404, {"error": f"No route {method} {path}"})
        except UnauthorizedError as e:
            self._respond(handler, 401, {"error": str(e)})
        except (InvalidRequestError, InvalidModelClassError) as e:
            # field presence/coercion is validated at the route boundary
            # (_field/_num_field/_b64_field/_int_param), so ValueError &
            # friends from inside Admin stay genuine 500s instead of being
            # masked as client errors with internal text echoed back
            self._respond(handler, 400, {"error": f"{type(e).__name__}: {e}"})
        except RolloutInFlightError as e:
            # exactly one live rollout per job: the conflict is the
            # resource's current state, so 409 (retry after the rollout
            # ends, or abort it) — typed for Client.update_inference_job
            self._respond(handler, 409, {"error": f"{type(e).__name__}: {e}"})
        except ArtifactCorruptError as e:
            # a damaged on-disk artifact (params/checkpoint): the client
            # gets the typed error cleanly, never a deserialize traceback
            self._respond(handler, 500, {"error": f"{type(e).__name__}: {e}"})
        except FrameTooLargeError as e:
            # the request's wire frame exceeds the shm ring: permanent for
            # this payload — 413, never the retryable 429
            self._respond(handler, 413, {"error": f"{type(e).__name__}: {e}"})
        except (QueueFullError, DeadlineUnmeetableError) as e:
            # serving overload, retryable backlog (docs/failure-model.md
            # "Overload faults"): 429 + Retry-After, same contract as the
            # dedicated predictor port
            self._respond(handler, 429,
                          {"error": f"{type(e).__name__}: {e}"},
                          headers=retry_after_headers(e))
        except ServerOverloadedError as e:
            # serving door out of in-flight capacity
            self._respond(handler, 503,
                          {"error": f"{type(e).__name__}: {e}"},
                          headers=retry_after_headers(e))
        except TimeoutError as e:
            # predict missed its SLO: a 504 the client may retry, not an
            # internal error — same contract as the dedicated predictor
            # port, and no spurious server-side traceback per miss
            self._respond(handler, 504, {"error": f"{type(e).__name__}: {e}"})
        except InsufficientChipsError as e:
            self._respond(handler, 503, {"error": f"{type(e).__name__}: {e}"})
        except (StaleEpochError, StaleAdminEpochError) as e:
            # this admin lost leadership mid-request (epoch fence fired at
            # the DB chokepoint or an agent refused a stale epoch): answer
            # like a standby — 503 + leader hint — so the client's
            # multi-address failover walks to the new leader
            self._respond(
                handler, 503,
                {"error": f"{type(e).__name__}: admin lost leadership; "
                          "retry against the leader",
                 "standby": True,
                 "leader": getattr(self.admin, "leader_hint",
                                   lambda: None)()},
                headers={"Retry-After": "1"})
        except Exception:
            # log the traceback server-side; never leak it to callers
            logger.error("unhandled error on %s %s:\n%s", method,
                         handler.path, traceback.format_exc())
            self._respond(handler, 500, {"error": "internal server error"})

    @staticmethod
    def _respond(handler, code: int, payload: Dict[str, Any],
                 headers: Optional[Dict[str, str]] = None) -> None:
        data = json.dumps(payload).encode()
        handler.send_response(code)
        handler.send_header("Content-Type", "application/json")
        handler.send_header("Content-Length", str(len(data)))
        for k, v in (headers or {}).items():
            handler.send_header(k, v)
        handler.end_headers()
        handler.wfile.write(data)
