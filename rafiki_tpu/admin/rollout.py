"""Safe live rollouts: canary, SLO-guarded rolling updates, automatic
rollback.

The reference Rafiki could only change what a RUNNING inference job
serves by tearing it down and redeploying — a full outage per model
update — and until this module the reproduction inherited that gap:
``create_inference_services`` deploys once and rolls back only on
*startup* failure. Every live-mutation primitive a safe rollout needs
already existed (``scale_inference_job``, ``drain_replicas``,
``Predictor.add/retire/unretire/drop_worker``); this controller composes
them into the missing robustness property — an operator (or the platform,
when a better trial finishes training) ships a new model version under
live traffic with zero dropped requests and a machine-enforced guarantee
that a bad version gets bounded blast radius and automatic rollback.

State machine (``constants.RolloutPhase``; at most ONE live rollout per
job, a second request answers typed 409)::

    CANARY ──healthy──▶ ROLLING ──all replaced──▶ DONE
      │                    │
      └──SLO breach / canary crash / deploy failure or timeout──▶ ROLLED_BACK
    (job stopped / admin shutdown / stale row swept at boot ──▶ ABORTED)

- **Canary**: one new-version replica is placed beside the incumbents
  and routed ``RAFIKI_ROLLOUT_CANARY_FRACTION`` of traffic via the
  predictor's version lanes (deterministic weighted counter — a request
  is served by exactly one version, never an ensemble across versions;
  a canary-lane failure fails over to the incumbents, so a bad canary
  costs the judge an error sample, never the client a request).
- **Judge**: over a trailing ``RAFIKI_ROLLOUT_JUDGE_WINDOW_S`` window the
  canary's error rate (errors + sheds) must stay within
  ``RAFIKI_ROLLOUT_ERR_DELTA`` of the incumbents' and its ok-latency p95
  within ``RAFIKI_ROLLOUT_P95_FACTOR`` × theirs (per-lane outcome series
  mirrored into the PR-6 registry as ``rafiki_rollout_requests_total`` /
  ``rafiki_rollout_request_seconds``). A verdict needs
  ``RAFIKI_ROLLOUT_MIN_REQUESTS`` canary samples; an idle job proceeds
  after 3× the window with a low-traffic note instead of stalling.
- **Rolling**: place ``RAFIKI_ROLLOUT_BATCH`` new replicas, gracefully
  drain as many old ones (the PR-2/PR-7 drain machinery — no in-flight
  request dropped), re-judge between batches.
- **Rollback**: on any breach, crash, or deploy failure/timeout the lane
  fraction drops to 0, incumbent capacity lost during rolling is
  restored, every new-version replica is drained, and the rollout row
  records the reason + signal snapshot (first-class events, like
  autoscaler decisions, surfaced in ``GET /fleet/health`` and counted in
  ``rafiki_rollout_rollbacks_total``). Doctor WARNs until an operator
  acks the rollback.

The autoscaler pauses its decisions for a job mid-rollout (and re-windows
after); control-plane recovery resolves a half-finished rollout at boot —
resume-as-done when the fleet is already fully new-version, rollback
otherwise — so a crashed admin can never strand one.

TEXT_GENERATION jobs roll through the same machine with
**stream-granularity** lanes (docs/failure-model.md "Stream
continuity"): a stream draws its version lane once at admission and
keeps it for life; mid-stream deaths charge an ``error`` sample to the
stream's lane so the judge sees them; each rolling drain waits out
``gen_resident_streams`` inside the drain budget and the worker hands
the rest back typed MIGRATING for door-side resume on same-version
siblings — a gen-job update drops zero streams.
"""

from __future__ import annotations

import collections
import logging
import threading
import time
from typing import Any, Dict, List, Optional

from rafiki_tpu import config
from rafiki_tpu.constants import (
    BudgetType,
    InferenceJobStatus,
    RolloutPhase,
    ServiceStatus,
    TrialStatus,
)

logger = logging.getLogger(__name__)

_TERMINAL_SVC = (ServiceStatus.STOPPED, ServiceStatus.ERRORED)
_MAX_ROW_EVENTS = 50


class RolloutError(Exception):
    """Base class for rollout control errors."""


class RolloutInFlightError(RolloutError):
    """A rollout is already in flight for this job — the HTTP door
    answers a typed 409; retry after it reaches a terminal phase (or
    abort it)."""


class _Aborted(Exception):
    """Internal: the run was told to stop (operator abort, job stopped,
    admin shutdown). ``rollback`` says whether a rollback pass should
    still run (an admin mid-shutdown tears everything down anyway)."""

    def __init__(self, reason: str, rollback: bool = True):
        super().__init__(reason)
        self.reason = reason
        self.rollback = rollback


class _Run:
    """One in-flight rollout (one background thread)."""

    def __init__(self, rollout_id: str, job_id: str, from_trial: str,
                 to_trial: str, from_version: int, to_version: int,
                 n_before: int, fraction: float, batch: int):
        self.rollout_id = rollout_id
        self.job_id = job_id
        self.from_trial = from_trial
        self.to_trial = to_trial
        self.from_version = from_version
        self.to_version = to_version
        self.n_before = n_before
        self.fraction = fraction
        self.batch = max(int(batch), 1)
        self.new_sids: List[str] = []
        self.events: List[Dict[str, Any]] = []
        self.thread: Optional[threading.Thread] = None
        self._abort_evt = threading.Event()
        self._abort_reason: Optional[str] = None
        self._abort_rollback = True

    def abort(self, reason: str, rollback: bool = True) -> None:
        self._abort_reason = reason
        self._abort_rollback = rollback
        self._abort_evt.set()

    def check_abort(self) -> None:
        if self._abort_evt.is_set():
            raise _Aborted(self._abort_reason or "aborted",
                           self._abort_rollback)

    def wait(self, timeout_s: float) -> None:
        if self._abort_evt.wait(timeout_s):
            self.check_abort()


class RolloutController:
    """One per Admin: owns every in-flight rollout run, the bounded
    event log, and the boot-time resolution of half-finished rollouts."""

    def __init__(self, admin) -> None:
        self._admin = admin
        self._services = admin.services
        self._db = admin.db
        self._lock = threading.Lock()
        self._runs: Dict[str, _Run] = {}  # guarded-by: _lock
        #: first-class decision log, newest last (fleet-health
        #: "rollouts"); append and snapshot race across threads
        self.events: collections.deque = (  # guarded-by: _lock
            collections.deque(maxlen=100))
        self._closed = threading.Event()
        from rafiki_tpu.utils.metrics import REGISTRY

        self._m_started = REGISTRY.counter(
            "rafiki_rollout_started_total",
            "rollouts started", ("job",))
        self._m_completed = REGISTRY.counter(
            "rafiki_rollout_completed_total",
            "rollouts that reached DONE", ("job",))
        self._m_rollbacks = REGISTRY.counter(
            "rafiki_rollout_rollbacks_total",
            "rollouts automatically rolled back", ("job",))

    # -- lifecycle ----------------------------------------------------------

    def is_active(self, inference_job_id: str) -> bool:
        """True while a rollout run is in flight for the job — the
        autoscaler pauses its decisions on this (and re-windows after).
        Registration IS the in-flight signal: a run sits in ``_runs``
        from the moment :meth:`start` reserves the job until its
        thread's finally-block removes it, so a not-yet-started thread
        (the start/registration window) already counts as in flight —
        two concurrent starts can never both pass the guard."""
        with self._lock:
            return inference_job_id in self._runs

    def stop(self) -> None:
        """Admin shutdown: every run exits NOW (marked ABORTED — the
        teardown that follows destroys the fleet either way, so a
        rollback pass would only fight it)."""
        self._closed.set()
        with self._lock:
            runs = list(self._runs.values())
        for run in runs:
            run.abort("admin shutdown", rollback=False)
        for run in runs:
            if run.thread is not None:
                run.thread.join(timeout=10)

    def abort_for_job(self, inference_job_id: str, reason: str) -> None:
        """The job is being stopped: end its rollout without a rollback
        pass (the stop tears the whole fleet down) and wait it out so the
        teardown never races a mid-flight placement."""
        with self._lock:
            run = self._runs.get(inference_job_id)
        if run is None:
            return
        run.abort(reason, rollback=False)
        if run.thread is not None:
            run.thread.join(
                timeout=float(config.SERVICE_DEPLOY_TIMEOUT_S) + 10)

    # -- operator API -------------------------------------------------------

    def start(self, inference_job_id: str, to_trial_id: str,
              canary_fraction: Optional[float] = None,
              batch: Optional[int] = None) -> Dict[str, Any]:
        """Begin a rollout of ``to_trial_id`` for a RUNNING inference
        job. Raises :class:`RolloutInFlightError` (→ 409) when one is
        already live, InvalidRequestError (→ 400) on a bad target."""
        from rafiki_tpu.admin.admin import InvalidRequestError

        with self._lock:
            run = self._runs.get(inference_job_id)
            if run is not None:
                raise RolloutInFlightError(
                    f"a rollout is already in flight for job "
                    f"{inference_job_id} (phase "
                    f"{self._phase_of(run)}); abort it or wait")
        # a LIVE row with no controller run is a dead admin's leftover
        # the boot pass missed (e.g. created between snapshot and crash):
        # sweep it so one stale row can never wedge the job forever
        for row in self._db.get_rollouts_by_phases(list(RolloutPhase.LIVE)):
            if row["inference_job_id"] == inference_job_id:
                self._db.mark_rollout_phase(
                    row["id"], RolloutPhase.ABORTED,
                    "stale rollout row with no controller run "
                    "(superseded)")
        inf = self._db.get_inference_job(inference_job_id)
        if inf is None or inf["status"] != InferenceJobStatus.RUNNING:
            raise InvalidRequestError(
                f"inference job {inference_job_id} is not RUNNING")
        if (inf.get("budget") or {}).get(BudgetType.ENSEMBLE_FUSED, 0):
            raise InvalidRequestError(
                "live rollouts are unsupported for ENSEMBLE_FUSED jobs: "
                "a fused worker co-locates every best trial, so there is "
                "no per-replica version to canary — redeploy instead")
        predictor = self._services.get_predictor(inference_job_id)
        if predictor is None:
            raise InvalidRequestError(
                f"inference job {inference_job_id} has no live predictor")
        live = self._services.live_inference_workers(inference_job_id)
        if not live:
            raise InvalidRequestError(
                f"inference job {inference_job_id} has no live replicas")
        trial = self._db.get_trial(to_trial_id)
        if trial is None or trial["status"] != TrialStatus.COMPLETED \
                or not trial.get("params_file_path"):
            raise InvalidRequestError(
                f"rollout target {to_trial_id} is not a COMPLETED trial "
                "with persisted params")
        sub = self._db.get_sub_train_job(trial["sub_train_job_id"])
        target_job = self._db.get_train_job(sub["train_job_id"]) \
            if sub else None
        serving_job = self._db.get_train_job(inf["train_job_id"])
        if target_job is None or serving_job is None \
                or target_job["task"] != serving_job["task"] \
                or target_job["user_id"] != serving_job["user_id"]:
            raise InvalidRequestError(
                f"rollout target {to_trial_id} does not serve this "
                "job's task (it must be a completed trial of the same "
                "task, owned by the same user)")
        if any(w["trial_id"] == to_trial_id for w in live):
            raise InvalidRequestError(
                f"job {inference_job_id} already serves trial "
                f"{to_trial_id}")
        fraction = (float(canary_fraction) if canary_fraction is not None
                    else float(config.ROLLOUT_CANARY_FRACTION))
        if not 0.0 < fraction <= 1.0:
            raise InvalidRequestError(
                f"canary_fraction {fraction} outside (0, 1]")
        from_version = max((w["model_version"] for w in live), default=0)
        to_version = from_version + 1
        # the most-replicated incumbent trial is the restore template
        by_trial: Dict[str, int] = {}
        for w in live:
            by_trial[w["trial_id"]] = by_trial.get(w["trial_id"], 0) + 1
        from_trial = max(sorted(by_trial), key=lambda t: by_trial[t])
        row = self._db.create_rollout(
            inference_job_id, from_trial, to_trial_id, from_version,
            to_version, len(live), RolloutPhase.CANARY)
        run = _Run(row["id"], inference_job_id, from_trial, to_trial_id,
                   from_version, to_version, len(live), fraction,
                   batch if batch is not None
                   else int(config.ROLLOUT_BATCH))
        with self._lock:
            if inference_job_id in self._runs:
                # a concurrent start won the race: this row never ran
                self._db.mark_rollout_phase(
                    row["id"], RolloutPhase.ABORTED,
                    "lost the start race to a concurrent rollout")
                raise RolloutInFlightError(
                    f"a rollout is already in flight for job "
                    f"{inference_job_id}")
            self._runs[inference_job_id] = run
        self._m_started.labels(inference_job_id).inc()
        self._event(run, "started",
                    detail=f"trial {from_trial[:8]} (v{from_version}) -> "
                           f"{to_trial_id[:8]} (v{to_version}), canary "
                           f"fraction {fraction:g}")
        run.thread = threading.Thread(
            target=self._run, args=(run,),
            name=f"rollout-{inference_job_id[:8]}", daemon=True)
        try:
            run.thread.start()
        except BaseException:
            # a thread that never starts would hold the in-flight
            # reservation (and its CANARY row) forever
            with self._lock:
                if self._runs.get(inference_job_id) is run:
                    del self._runs[inference_job_id]
            self._db.mark_rollout_phase(
                row["id"], RolloutPhase.ABORTED,
                "rollout thread could not start")
            raise
        return self._view(self._db.get_rollout(row["id"]))

    def status(self, inference_job_id: str) -> Optional[Dict[str, Any]]:
        """The job's newest rollout (live or terminal), with the live
        per-lane signal snapshot while one is in flight."""
        rows = self._db.get_rollouts_of_inference_job(inference_job_id)
        if not rows:
            return None
        view = self._view(rows[0])
        if view["phase"] in RolloutPhase.LIVE:
            predictor = self._services.get_predictor(inference_job_id)
            if predictor is not None:
                view["signals"] = predictor.rollout_stats(
                    float(config.ROLLOUT_JUDGE_WINDOW_S))
        return view

    def abort(self, inference_job_id: str) -> Dict[str, Any]:
        """Operator abort: a LIVE rollout rolls back (reason "operator
        abort"); a stale LIVE row with no run is marked ABORTED."""
        from rafiki_tpu.admin.admin import InvalidRequestError

        with self._lock:
            run = self._runs.get(inference_job_id)
        if run is not None:
            run.abort("operator abort", rollback=True)
            thread = run.thread
            if thread is not None:
                thread.join(
                    timeout=float(config.SERVICE_DEPLOY_TIMEOUT_S)
                    + float(config.AUTOSCALE_DRAIN_S) + 10)
            return self.status(inference_job_id) or {}
        for row in self._db.get_rollouts_by_phases(list(RolloutPhase.LIVE)):
            if row["inference_job_id"] == inference_job_id:
                self._db.mark_rollout_phase(
                    row["id"], RolloutPhase.ABORTED,
                    "operator abort (no live controller run)")
                return self._view(self._db.get_rollout(row["id"]))
        raise InvalidRequestError(
            f"no rollout in flight for job {inference_job_id}")

    def ack(self, inference_job_id: str) -> Dict[str, Any]:
        """Operator acknowledgment of the newest unacked rollback —
        clears the doctor WARN."""
        from rafiki_tpu.admin.admin import InvalidRequestError

        # ROLLED_BACK only, matching doctor's unacked scan exactly — an
        # ack landing on an unacked ABORTED row would "succeed" while
        # the rollback WARN it was meant to clear kept standing
        for row in self._db.get_rollouts_of_inference_job(
                inference_job_id):
            if row["phase"] == RolloutPhase.ROLLED_BACK \
                    and not row["operator_ack"]:
                self._db.ack_rollout(row["id"])
                return self._view(self._db.get_rollout(row["id"]))
        raise InvalidRequestError(
            f"no unacknowledged rollback for job {inference_job_id}")

    # -- the run ------------------------------------------------------------

    def _run(self, run: _Run) -> None:
        try:
            if not self._phase_canary(run):
                return  # rolled back
            if not self._phase_rolling(run):
                return
            self._finish(run)
        except _Aborted as a:
            if a.rollback:
                self._rollback(run, a.reason)
            else:
                self._event(run, "aborted", reason=a.reason)
                self._db.mark_rollout_phase(
                    run.rollout_id, RolloutPhase.ABORTED, a.reason)
        except Exception as e:
            logger.exception("rollout %s failed; rolling back",
                             run.rollout_id[:8])
            self._rollback(run, f"{type(e).__name__}: {e}")
        finally:
            with self._lock:
                if self._runs.get(run.job_id) is run:
                    del self._runs[run.job_id]

    def _check_job(self, run: _Run) -> None:
        run.check_abort()
        if self._closed.is_set():
            raise _Aborted("admin shutdown", rollback=False)
        inf = self._db.get_inference_job(run.job_id)
        if inf is None or inf["status"] != InferenceJobStatus.RUNNING:
            raise _Aborted("inference job left RUNNING mid-rollout",
                           rollback=False)

    def _phase_canary(self, run: _Run) -> bool:
        """Deploy one new-version replica, route it ``fraction`` of
        traffic, judge it over the trailing window. Returns False after
        a rollback."""
        predictor = self._services.get_predictor(run.job_id)
        deploy_deadline = time.monotonic() \
            + float(config.SERVICE_DEPLOY_TIMEOUT_S) + 5.0
        self._check_job(run)
        try:
            sid = self._services.deploy_version_replica(
                run.job_id, run.to_trial, run.to_version)
        # lint: absorb(a failed canary deploy IS a rollback trigger; _rollback records and logs it)
        except Exception as e:
            self._rollback(run, f"canary deploy failed: {e}")
            return False
        run.new_sids.append(sid)
        if time.monotonic() > deploy_deadline:
            self._rollback(run, "canary deploy timeout")
            return False
        # lane membership BEFORE the replica becomes routable: a request
        # landing between add_worker and the lane update would ensemble
        # the unjudged canary with the incumbents (and book its outcome
        # against the incumbent baseline). new_version keys the canary
        # lane's prediction-cache traffic apart from the incumbents'
        # (predictor/result_cache.py: a cached canary answer can never
        # leak into the incumbent lane)
        predictor.set_rollout_lane(set(run.new_sids), run.fraction,
                                   new_version=run.to_version)
        predictor.add_worker(sid, run.to_trial)
        self._event(run, "canary_deployed",
                    detail=f"replica {sid[:8]} at fraction "
                           f"{run.fraction:g}")
        window = max(float(config.ROLLOUT_JUDGE_WINDOW_S), 0.5)
        min_req = max(int(config.ROLLOUT_MIN_REQUESTS), 0)
        start = time.monotonic()
        while True:
            self._check_job(run)
            breach, signals = self._breach(run, predictor)
            if breach is not None:
                self._rollback(run, breach, signals)
                return False
            elapsed = time.monotonic() - start
            if elapsed >= window:
                stats = predictor.rollout_stats(window)
                if stats["canary"]["requests"] >= min_req:
                    self._event(run, "canary_healthy", signals=stats)
                    return True
                if elapsed >= window * 3:
                    # an idle job must still be updatable: proceed, but
                    # say the verdict rests on thin traffic
                    self._event(
                        run, "canary_low_traffic",
                        detail=f"only {stats['canary']['requests']} "
                               f"canary request(s) in {elapsed:.1f}s; "
                               "proceeding without a latency verdict",
                        signals=stats)
                    return True
            run.wait(0.1)

    def _phase_rolling(self, run: _Run) -> bool:
        """Replace the incumbents in bounded batches: place new, drain
        old, re-judge between batches. Returns False after a rollback."""
        predictor = self._services.get_predictor(run.job_id)
        self._db.mark_rollout_phase(run.rollout_id, RolloutPhase.ROLLING)
        self._event(run, "rolling", detail=f"batch size {run.batch}")
        stalls = 0
        while True:
            self._check_job(run)
            live = self._services.live_inference_workers(run.job_id)
            old = [w for w in live
                   if w["model_version"] != run.to_version]
            new = [w for w in live
                   if w["model_version"] == run.to_version]
            if not old:
                break
            # traffic share tracks the replica split through the whole
            # phase (the canary fraction only governed the CANARY phase)
            predictor.set_rollout_lane(
                set(run.new_sids),
                len(new) / max(len(old) + len(new), 1),
                new_version=run.to_version)
            # keep total capacity >= n_before: place first, then drain.
            # The canary already counts toward the n_before target, so
            # the final fleet converges to exactly the pre-rollout size
            # (and a stuck drain can never mint replicas past it)
            to_place = min(run.batch, max(0, run.n_before - len(new)))
            placed = 0
            for _ in range(to_place):
                try:
                    sid = self._services.deploy_version_replica(
                        run.job_id, run.to_trial, run.to_version)
                # lint: absorb(a mid-rolling deploy failure IS a rollback trigger; _rollback records and logs it)
                except Exception as e:
                    self._rollback(
                        run, f"deploy failure during rolling replace: "
                             f"{e}")
                    return False
                run.new_sids.append(sid)
                placed += 1
                # same ordering rule as the canary: the replica joins
                # the lane set before add_worker makes it routable, so
                # it can never serve (or be judged as) incumbent traffic
                predictor.set_rollout_lane(
                    set(run.new_sids),
                    (len(new) + placed)
                    / max(len(old) + len(new) + placed, 1),
                    new_version=run.to_version)
                predictor.add_worker(sid, run.to_trial)
            victims = [w["service_id"] for w in old[:run.batch]]
            _, removed = self._services.drain_replicas(
                run.job_id, victims)
            if removed or placed:
                stalls = 0
            else:
                # a drain can transiently fail under exactly the load a
                # live rollout exists for (the victim is restored to the
                # fan-out) — retry a bounded number of times before
                # declaring the replace stalled and rolling back a
                # version the judge still considers healthy
                stalls += 1
                if stalls >= 3:
                    self._rollback(
                        run, "rolling replace stalled: victims could "
                             "not be drained in 3 consecutive attempts "
                             "and the fleet is at target size")
                    return False
                run.wait(0.5)
            breach, signals = self._breach(run, predictor)
            if breach is not None:
                self._rollback(run, breach, signals)
                return False
            self._event(
                run, "batch_replaced",
                detail=f"+{placed} new / -{len(removed)} old "
                       f"({len(new) + placed} of {run.n_before} on "
                       f"v{run.to_version})")
        return True

    def _finish(self, run: _Run) -> None:
        predictor = self._services.get_predictor(run.job_id)
        if predictor is not None:
            # promote BEFORE clearing the lane: a request racing the
            # promotion either keys on the (still-set) canary lane or on
            # the already-bumped serving version — never on the replaced
            # version. The flush then drops every older version's
            # entries (the canary's own fills stay: they are the new
            # incumbent's warm start) and bumps the fill epoch so a
            # forward resolved against the replaced fleet can't land.
            predictor.set_serving_version(run.to_version)
            predictor.clear_rollout_lane()
        from rafiki_tpu.predictor.result_cache import get_cache

        get_cache().flush_job(run.job_id, keep_version=run.to_version,
                              reason="rollout done")
        self._db.mark_rollout_phase(run.rollout_id, RolloutPhase.DONE)
        self._m_completed.labels(run.job_id).inc()
        self._event(run, "completed",
                    detail=f"job serves trial {run.to_trial[:8]} "
                           f"(v{run.to_version}) on "
                           f"{len(run.new_sids)} replica(s)")
        logger.warning("rollout %s DONE: job %s now serves trial %s",
                       run.rollout_id[:8], run.job_id[:8],
                       run.to_trial[:8])

    # -- the SLO judge ------------------------------------------------------

    def _breach(self, run: _Run, predictor):
        """One judge pass: (breach_reason | None, signal snapshot).
        Canary crash is a breach regardless of traffic; error-rate and
        latency verdicts need ``RAFIKI_ROLLOUT_MIN_REQUESTS`` canary
        samples in the window."""
        for sid in run.new_sids:
            svc = self._db.get_service(sid)
            if svc is None or svc["status"] in _TERMINAL_SVC:
                return (f"new-version replica {sid[:8]} "
                        f"{'vanished' if svc is None else svc['status']}",
                        None)
        window = max(float(config.ROLLOUT_JUDGE_WINDOW_S), 0.5)
        stats = predictor.rollout_stats(window)
        can, inc = stats["canary"], stats["incumbent"]
        if can["requests"] < max(int(config.ROLLOUT_MIN_REQUESTS), 1):
            return None, stats
        can_rate = (can["errors"] + can["shed"]) / can["requests"]
        inc_rate = ((inc["errors"] + inc["shed"]) / inc["requests"]
                    if inc["requests"] else 0.0)
        delta = float(config.ROLLOUT_ERR_DELTA)
        if can_rate - inc_rate > delta:
            return (f"canary error rate {can_rate:.0%} exceeds incumbent "
                    f"{inc_rate:.0%} by more than {delta:.0%}", stats)
        factor = float(config.ROLLOUT_P95_FACTOR)
        if can["p95_s"] is not None and inc["p95_s"] is not None \
                and can["p95_s"] > inc["p95_s"] * factor + 0.005:
            return (f"canary p95 {can['p95_s'] * 1000:.0f}ms exceeds "
                    f"{factor:g}x incumbent p95 "
                    f"{inc['p95_s'] * 1000:.0f}ms", stats)
        return None, stats

    # -- rollback -----------------------------------------------------------

    def _rollback(self, run: _Run, reason: str,
                  signals: Optional[Dict[str, Any]] = None) -> None:
        logger.warning("rollout %s ROLLING BACK job %s: %s",
                       run.rollout_id[:8], run.job_id[:8], reason)
        self._event(run, "rollback", reason=reason, signals=signals)
        self._rollback_fleet(run.job_id, run.to_version, run.from_trial,
                             run.from_version, run.n_before, run.new_sids)
        self._db.mark_rollout_phase(
            run.rollout_id, RolloutPhase.ROLLED_BACK, reason)
        self._m_rollbacks.labels(run.job_id).inc()

    def _rollback_fleet(self, job_id: str, to_version: int,
                        from_trial: str, from_version: int,
                        n_before: int, new_sids: List[str]) -> None:
        """Restore the incumbent fleet: traffic off the new version
        first, incumbent capacity restored, then every new-version
        replica gracefully drained. Shared by live rollbacks and the
        boot-time resolution of a crashed admin's half-finished rollout."""
        predictor = self._services.get_predictor(job_id)
        if predictor is not None and new_sids:
            predictor.set_rollout_lane(set(new_sids), 0.0)
        # every cached answer of the aborted version dies NOW — before
        # the restore places replicas — and the epoch bump drops fills
        # from forwards still in flight against it. Full flush (not
        # keep_version): rollbacks are rare, and a cold cache is cheaper
        # than reasoning about which incumbent entries survived the
        # churn. A later rollout REUSES this to_version number, so its
        # entries must be provably gone (predictor/result_cache.py).
        from rafiki_tpu.predictor.result_cache import get_cache

        get_cache().flush_job(job_id, reason="rollback")
        live = self._services.live_inference_workers(job_id)
        old_live = [w for w in live if w["model_version"] != to_version]
        deficit = n_before - len(old_live)
        by_trial: Dict[str, int] = {}
        for w in old_live:
            by_trial[w["trial_id"]] = by_trial.get(w["trial_id"], 0) + 1
        for _ in range(max(deficit, 0)):
            trial = (min(sorted(by_trial), key=lambda t: by_trial[t])
                     if by_trial else from_trial)
            try:
                sid = self._services.deploy_version_replica(
                    job_id, trial, from_version)
            except Exception:
                # incumbents still serve, just thinner — the autoscaler
                # (resumed after this rollout ends) can regrow them
                logger.exception(
                    "rollback: could not restore an incumbent replica "
                    "of %s for job %s", trial[:8], job_id[:8])
                break
            by_trial[trial] = by_trial.get(trial, 0) + 1
            if predictor is not None:
                predictor.add_worker(sid, trial)
        still_live = {w["service_id"]
                      for w in self._services.live_inference_workers(
                          job_id)}
        victims = [s for s in new_sids if s in still_live]
        if victims:
            try:
                self._services.drain_replicas(job_id, victims)
            except Exception:
                logger.exception(
                    "rollback: draining new-version replicas of job %s "
                    "failed", job_id[:8])
        if predictor is not None:
            # pin the cache key back to the restored generation: a
            # predictor ADOPTED over a mixed mid-rollout fleet read its
            # serving version off the worker rows' max — which is the
            # version this rollback just retired (live rollbacks no-op:
            # only _finish ever bumps the serving version)
            predictor.set_serving_version(from_version)
            predictor.clear_rollout_lane()

    # -- boot-time resolution (admin/recovery.py) ---------------------------

    def recover_on_boot(self) -> None:
        """Resolve every rollout a dead admin left in a LIVE phase —
        never strand one. The adopted fleet's worker rows carry each
        replica's model_version, so the verdict is mechanical: all
        replicas already new-version → the rolling phase had finished,
        mark DONE; any incumbents left → roll back (the judge's window
        died with the old admin, and a half-judged version must not keep
        taking traffic on a restarted control plane's watch)."""
        for row in self._db.get_rollouts_by_phases(list(RolloutPhase.LIVE)):
            job_id = row["inference_job_id"]
            try:
                inf = self._db.get_inference_job(job_id)
                if inf is None \
                        or inf["status"] != InferenceJobStatus.RUNNING:
                    self._db.mark_rollout_phase(
                        row["id"], RolloutPhase.ABORTED,
                        "inference job not RUNNING after control-plane "
                        "restart")
                    continue
                live = self._services.live_inference_workers(job_id)
                old = [w for w in live
                       if w["model_version"] != row["to_version"]]
                new = [w for w in live
                       if w["model_version"] == row["to_version"]]
                if new and not old:
                    self._db.mark_rollout_phase(
                        row["id"], RolloutPhase.DONE,
                        "completed by recovery: the fleet was already "
                        "fully on the new version")
                    self._log_event(
                        job_id, row["id"], "completed",
                        reason="resumed as done by recovery")
                    continue
                reason = ("control-plane restart mid-rollout: rolled "
                          "back to the incumbent version")
                self._log_event(job_id, row["id"], "rollback",
                                reason=reason)
                self._rollback_fleet(
                    job_id, row["to_version"], row["from_trial_id"],
                    row["from_version"], int(row["n_replicas_before"]),
                    [w["service_id"] for w in new])
                self._db.mark_rollout_phase(
                    row["id"], RolloutPhase.ROLLED_BACK, reason)
                self._m_rollbacks.labels(job_id).inc()
            except Exception:
                logger.exception(
                    "boot-time rollout resolution failed for %s "
                    "(job %s)", row["id"][:8], job_id[:8])

    # -- observability ------------------------------------------------------

    def _phase_of(self, run: _Run) -> str:
        row = self._db.get_rollout(run.rollout_id)
        return row["phase"] if row else "?"

    def _event(self, run: _Run, name: str, detail: Optional[str] = None,
               reason: Optional[str] = None,
               signals: Optional[Dict[str, Any]] = None) -> None:
        event = {"ts": time.time(), "job_id": run.job_id,
                 "rollout_id": run.rollout_id, "event": name}
        if detail:
            event["detail"] = detail
        if reason:
            event["reason"] = reason
        if signals:
            event["signals"] = signals
        run.events.append(event)
        with self._lock:
            self.events.append(event)
        try:
            self._db.update_rollout_events(
                run.rollout_id, run.events[-_MAX_ROW_EVENTS:])
        except Exception:
            logger.exception("persisting rollout event failed")

    def _log_event(self, job_id: str, rollout_id: str, name: str,
                   reason: Optional[str] = None) -> None:
        event = {"ts": time.time(), "job_id": job_id,
                 "rollout_id": rollout_id, "event": name}
        if reason:
            event["reason"] = reason
        with self._lock:
            self.events.append(event)
        try:
            row = self._db.get_rollout(rollout_id)
            events = (row["events"] if row else []) + [event]
            self._db.update_rollout_events(
                rollout_id, events[-_MAX_ROW_EVENTS:])
        except Exception:
            logger.exception("persisting rollout event failed")

    @staticmethod
    def _view(row: Optional[Dict[str, Any]]) -> Dict[str, Any]:
        if row is None:
            return {}
        return {
            "id": row["id"],
            "inference_job_id": row["inference_job_id"],
            "from_trial_id": row["from_trial_id"],
            "to_trial_id": row["to_trial_id"],
            "from_version": row["from_version"],
            "to_version": row["to_version"],
            "n_replicas_before": row["n_replicas_before"],
            "phase": row["phase"],
            "reason": row["reason"],
            "operator_ack": row["operator_ack"],
            "events": row["events"],
            "datetime_started": row["datetime_started"],
            "datetime_stopped": row["datetime_stopped"],
        }

    def report(self) -> Dict[str, Any]:
        """The fleet-health "rollouts" section: every in-flight rollout
        with its live lane signals, plus the recent event log (rollback
        reasons + signal snapshots ride here)."""
        with self._lock:
            active_jobs = dict(self._runs)
            recent = list(self.events)[-20:]
        active: Dict[str, Any] = {}
        for job_id, run in active_jobs.items():
            entry = {
                "rollout_id": run.rollout_id,
                "phase": self._phase_of(run),
                "to_trial_id": run.to_trial,
                "to_version": run.to_version,
                "canary_fraction": run.fraction,
            }
            predictor = self._services.get_predictor(job_id)
            if predictor is not None:
                entry["signals"] = predictor.rollout_stats(
                    float(config.ROLLOUT_JUDGE_WINDOW_S))
            active[job_id] = entry
        return {"active": active, "events": recent}
