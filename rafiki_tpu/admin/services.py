"""Deployment engine: turns job rows into running executor services.

Parity with the reference's ServicesManager (reference
rafiki/admin/services_manager.py:28-403):

- train jobs: the chip budget is split evenly across sub-train-jobs (one per
  model), one executor per chip with a no-chip fallback executor when the
  budget is 0 (reference :190-202, :107-135 — there per GPU container, here
  per granted chip);
- inference jobs: for each of the best ``INFERENCE_MAX_BEST_TRIALS`` trials,
  ``INFERENCE_WORKER_REPLICAS_PER_TRIAL`` serving executors plus one predictor
  (reference :53-87);
- deployment waits until services report RUNNING and rolls back on failure
  (reference :279-290, :131-135);
- train-job status is derived from worker-service states (reference :160-184).
"""

from __future__ import annotations

import logging
import threading
import time
from typing import Any, Dict, List, Optional

from rafiki_tpu import config
from rafiki_tpu.advisor.advisor import AdvisorStore
from rafiki_tpu.cache.queue import Broker
from rafiki_tpu.constants import (
    BudgetType,
    InferenceJobStatus,
    ServiceStatus,
    ServiceType,
    TaskType,
    TrainJobStatus,
    TrialStatus,
)
from rafiki_tpu.db.database import Database
from rafiki_tpu.placement.manager import (
    InsufficientChipsError,
    PlacementManager,
)
from rafiki_tpu.predictor.predictor import Predictor
from rafiki_tpu.utils import chaos
from rafiki_tpu.worker.inference import InferenceWorker
from rafiki_tpu.worker.train import TrainWorker

logger = logging.getLogger(__name__)


class ServiceDeploymentError(Exception):
    pass


def _chaos_deploy(inference_job_id: str, trial_id: str) -> None:
    """RAFIKI_CHAOS site=deploy: the place-new-replica chokepoint shared
    by the initial deploy, autoscaler scale-ups, and the rollout
    controller's canary/rolling placements. `error`/`drop` raise the
    typed deploy failure (the deterministic canary-failure rollback
    drill); `delay` models a slow deploy (against the rollout's deploy
    deadline, the deploy-timeout drill)."""
    rule = chaos.hit(chaos.SITE_DEPLOY, f"{inference_job_id}/{trial_id}")
    if rule is None:
        return
    if rule.action == chaos.ACTION_DELAY:
        chaos.sleep_for(rule)
        return
    raise ServiceDeploymentError(
        f"chaos-injected deploy failure placing a replica of trial "
        f"{trial_id} for job {inference_job_id}")


class ServicesManager:
    def __init__(
        self,
        db: Database,
        placement: PlacementManager,
        advisor_store: AdvisorStore,
        broker: Broker,
        send_event,
        params_dir: Optional[str] = None,
        arbiter=None,
    ):
        """``arbiter`` (placement/hosts.py ChipBudgetArbiter) mediates
        chip loans between the serving and training planes: autoscaler
        scale-ups may borrow idle trial chips through it, and a train
        executor that can't allocate reclaims them (the arbiter's reclaim
        callback is installed here — reclaim works whether or not the
        autoscaler loop itself is running)."""
        self._db = db
        self._placement = placement
        self._advisors = advisor_store
        self._broker = broker
        self._send_event = send_event
        self._params_dir = params_dir or config.PARAMS_DIR
        self._predictors: Dict[str, Predictor] = {}
        # inference_job_id -> PredictorServer (config.PREDICTOR_PORTS)
        self._predict_servers: Dict[str, object] = {}
        self._lock = threading.Lock()
        self._arbiter = arbiter
        if arbiter is not None:
            arbiter.set_reclaim_callback(self.reclaim_borrowed)
        # service_ids mid-graceful-drain (elastic scale-down): a second
        # scale-down landing during a drain must pick OTHER victims (or
        # no-op) — never double-drain, never double-count
        self._scale_lock = threading.Lock()
        self._scale_draining: set = set()

    # -- train -------------------------------------------------------------

    def create_train_services(self, train_job_id: str) -> None:
        job = self._db.get_train_job(train_job_id)
        assert job is not None
        sub_jobs = self._db.get_sub_train_jobs_of_train_job(train_job_id)
        budget = job["budget"]
        total_chips = int(
            budget.get(
                BudgetType.CHIP_COUNT, budget.get(BudgetType.GPU_COUNT, 0)
            )
        )
        avail = getattr(self._placement, "allocator", None)
        if avail is not None:
            # Clamp to the host's static capacity (asking for more chips than
            # exist downsizes the job, like the reference's even GPU split,
            # reference services_manager.py:190-202). Chips merely *busy* are
            # NOT clamped away: allocating them raises InsufficientChipsError
            # and the deploy rolls back — never silently share devices with
            # a running job.
            total_chips = min(total_chips, avail.total_chips)
        chips_per_sub = total_chips // len(sub_jobs) if sub_jobs else 0
        # CHIPS_PER_TRIAL > 1 gives each trial executor its own multi-chip
        # mesh (the executor's device grant IS its mesh — see
        # worker/train.py set_device_grant -> parallel.get_default_mesh), so
        # a single trial trains data/tensor/sequence-parallel across chips.
        # The reference could never do this: 1 GPU per worker, hard-wired
        # (reference services_manager.py:117-126).
        chips_per_trial = max(int(budget.get(BudgetType.CHIPS_PER_TRIAL, 1)), 1)
        if avail is not None:
            # one executor's grant can never span hosts: clamp the per-trial
            # mesh to the largest single-host inventory (downsize, don't
            # fail — same policy as the CHIP_COUNT clamp above). Single-host
            # allocators report their whole inventory.
            max_per_service = getattr(
                avail, "max_chips_per_service", avail.total_chips)
            if chips_per_trial > max_per_service > 0:
                logger.info(
                    "CHIPS_PER_TRIAL=%d exceeds the largest host (%d chips); "
                    "downsizing the per-trial mesh", chips_per_trial,
                    max_per_service)
                chips_per_trial = max_per_service

        created: List[str] = []
        try:
            for sub in sub_jobs:
                if chips_per_sub == 0:
                    # 0-chip fallback executor (shared devices)
                    workers = [0]
                elif chips_per_sub < chips_per_trial:
                    # downsized grant, like the chip-count clamp above —
                    # still one multi-chip executor rather than failing
                    workers = [chips_per_sub]
                else:
                    workers = [chips_per_trial] * (
                        chips_per_sub // chips_per_trial
                    )
                    stranded = chips_per_sub % chips_per_trial
                    if stranded:
                        # uniform grants on purpose: a smaller leftover
                        # executor would compile its own program instead of
                        # sharing the cached step — but say so
                        logger.info(
                            "sub_train_job %s: %d of %d chips idle "
                            "(CHIPS_PER_TRIAL=%d does not divide the "
                            "per-model share)", sub["id"], stranded,
                            chips_per_sub, chips_per_trial)
                for n_chips_each in workers:
                    sid = self._create_train_worker(sub["id"], n_chips_each)
                    created.append(sid)
            self._wait_until_services_running(created)
            self._db.mark_train_job_as_running(train_job_id)
        except Exception:
            # roll back partial deployments (reference :131-135)
            for sid in created:
                self._destroy_service(sid, wait=False)
            self._db.mark_train_job_as_errored(train_job_id)
            raise

    def _create_train_worker(self, sub_train_job_id: str, n_chips: int) -> str:
        service = self._db.create_service(ServiceType.TRAIN, replicas=1)
        self._db.create_train_job_worker(service["id"], sub_train_job_id)
        worker = TrainWorker(
            sub_train_job_id,
            self._db,
            self._advisors,
            send_event=self._send_event,
            params_dir=self._params_dir,
        )
        def place():
            return self._placement.create_service(
                service["id"], ServiceType.TRAIN, worker.start,
                n_chips=n_chips,
                # declarative payload so process/remote placements can
                # launch the worker without the closure
                extra={"sub_train_job_id": sub_train_job_id},
            )

        try:
            try:
                ctx = place()
            except InsufficientChipsError:
                # chip-budget arbitration: the chips this trial wants may
                # be ON LOAN to the serving plane (autoscaler borrow).
                # Training has priority over borrowed capacity — reclaim
                # (graceful scale-down of borrowed replicas) and retry
                # once before giving up.
                if (self._arbiter is None
                        or self._arbiter.reclaim_for_training(n_chips) <= 0):
                    raise
                logger.info(
                    "retrying train worker %s after reclaiming borrowed "
                    "serving chips", service["id"][:8])
                ctx = place()
        except Exception:
            # the DB rows exist but placement never started the service
            # (e.g. chips busy) — close the row so the rollback in
            # create_train_services (which only sees *returned* sids)
            # doesn't leave a phantom STARTED service behind
            self._db.mark_service_as_stopped(service["id"])
            raise
        try:
            # record the chip indices actually granted by the allocator
            self._db.update_service_chips(service["id"], ctx.chips)
        except Exception:
            # placement DID start the worker: tear it down, not just the row
            self._destroy_service(service["id"], wait=False)
            raise
        return service["id"]

    def stop_sub_train_job_services(self, sub_train_job_id: str) -> None:
        for w in self._db.get_workers_of_sub_train_job(sub_train_job_id):
            self._destroy_service(w["service_id"], wait=False)
        # the advisor session is keyed by sub_train_job_id; drop its GP
        # history now that no more trials will be proposed
        self._advisors.delete_advisor(sub_train_job_id)

    def stop_train_services(self, train_job_id: str) -> None:
        for w in self._db.get_workers_of_train_job(train_job_id):
            self._destroy_service(w["service_id"], wait=False)
        for sub in self._db.get_sub_train_jobs_of_train_job(train_job_id):
            self._advisors.delete_advisor(sub["id"])
        self.refresh_train_job_status(train_job_id)

    def refresh_train_job_status(self, train_job_id: str) -> None:
        """Derive job status from worker service states (reference :160-184)."""
        job = self._db.get_train_job(train_job_id)
        if job is None or job["status"] in (
            TrainJobStatus.STOPPED,
            TrainJobStatus.ERRORED,
        ):
            return
        workers = self._db.get_workers_of_train_job(train_job_id)
        statuses = []
        for w in workers:
            svc = self._db.get_service(w["service_id"])
            if svc:
                statuses.append(svc["status"])
        if not statuses:
            return
        if all(
            s in (ServiceStatus.STOPPED, ServiceStatus.ERRORED) for s in statuses
        ):
            if any(s == ServiceStatus.ERRORED for s in statuses):
                self._db.mark_train_job_as_errored(train_job_id)
            else:
                self._db.mark_train_job_as_stopped(train_job_id)

    def refresh_inference_job_status(
        self, inference_job_id: str
    ) -> Optional[str]:
        """Serving analogue of refresh_train_job_status (fleet health):
        when EVERY serving replica of an inference job is terminal — e.g.
        its hosts died and the heartbeat monitor errored their services —
        the job can never answer a query again, so it must reach a
        terminal status in the store without operator action. Returns the
        new job status when a transition happened, else None."""
        inf = self._db.get_inference_job(inference_job_id)
        if inf is None or inf["status"] in (
            InferenceJobStatus.STOPPED,
            InferenceJobStatus.ERRORED,
        ):
            return None
        statuses = []
        for w in self._db.get_workers_of_inference_job(inference_job_id):
            svc = self._db.get_service(w["service_id"])
            if svc:
                statuses.append(svc["status"])
        if not statuses or not all(
            s in (ServiceStatus.STOPPED, ServiceStatus.ERRORED)
            for s in statuses
        ):
            return None
        return self._teardown_serving(
            inference_job_id,
            errored=any(s == ServiceStatus.ERRORED for s in statuses))

    def _teardown_serving(self, inference_job_id: str,
                          errored: bool) -> str:
        """Shared serving-teardown tail: drop the predictor (and its
        dedicated port), close the predictor service row, and mark the
        job terminal. Used by the operator stop path and the all-replicas-
        dead refresh so the two cannot drift."""
        inf = self._db.get_inference_job(inference_job_id)
        with self._lock:
            self._predictors.pop(inference_job_id, None)
            psrv = self._predict_servers.pop(inference_job_id, None)
        if psrv is not None:
            psrv.stop()
        # the job's cached predictions die with its serving head: a
        # redeploy under the same app must never answer from the torn-
        # down fleet's cache (predictor/result_cache.py; the epoch bump
        # also drops in-flight fills that raced this teardown)
        from rafiki_tpu.predictor.result_cache import get_cache

        get_cache().flush_job(inference_job_id, reason="teardown")
        if inf and inf.get("predictor_service_id"):
            self._db.mark_service_as_stopped(inf["predictor_service_id"])
        if errored:
            self._db.mark_inference_job_as_errored(inference_job_id)
            return InferenceJobStatus.ERRORED
        self._db.mark_inference_job_as_stopped(inference_job_id)
        return InferenceJobStatus.STOPPED

    # -- inference -----------------------------------------------------------

    def create_inference_services(self, inference_job_id: str) -> Predictor:
        inf_job = self._db.get_inference_job(inference_job_id)
        assert inf_job is not None
        train_job = self._db.get_train_job(inf_job["train_job_id"])
        assert train_job is not None
        best_trials = self._db.get_best_trials_of_train_job(
            train_job["id"], max_count=config.INFERENCE_MAX_BEST_TRIALS
        )
        if not best_trials:
            self._db.mark_inference_job_as_errored(inference_job_id)
            raise ServiceDeploymentError(
                f"Train job {train_job['id']} has no completed trials"
            )
        # generative serving (docs/serving-generation.md): one BEST trial
        # serves the job — a token stream answers from exactly one model
        # (there is no cross-trial ensembling of incremental deltas), so
        # extra best trials would be dead weight; replicas still scale it
        generative = train_job["task"] == TaskType.TEXT_GENERATION
        if generative:
            best_trials = best_trials[:1]
        created: List[str] = []
        worker_trials: Dict[str, str] = {}
        # Capacity-aware replica count. Replicas buy capacity only when they
        # get their own chip, and redundancy only when they are separate
        # processes; same-chip replicas in one process just split batches —
        # halving batch occupancy and doubling per-query dispatches (the
        # reference's 2 replicas each got their own GPU,
        # reference services_manager.py:390-395 + config.py:10-11).
        n_replicas = config.INFERENCE_WORKER_REPLICAS_PER_TRIAL
        # CHIPS_PER_WORKER (inference budget): every serving executor gets
        # a multi-chip mesh — its worker sets the device grant
        # (worker/inference.py) and the model's pjit'd predict shards the
        # batch/params over those chips. The serving analogue of
        # CHIPS_PER_TRIAL; the reference pinned serving to 1 GPU/worker
        # (reference services_manager.py:390-395).
        budget = inf_job.get("budget") or {}
        chips_per_worker = max(
            int(budget.get(BudgetType.CHIPS_PER_WORKER, 1)), 1)
        alloc = getattr(self._placement, "allocator", None)
        if alloc is not None:
            # one worker's grant can never span hosts: clamp to the
            # largest single-host inventory, exactly like the
            # CHIPS_PER_TRIAL clamp above (fleet-total would let a
            # 6-chip ask through a 2x4-chip fleet and silently degrade
            # to the local fallback)
            max_per_service = getattr(
                alloc, "max_chips_per_service", alloc.total_chips)
            if chips_per_worker > max_per_service > 0:
                logger.warning(
                    "CHIPS_PER_WORKER=%d exceeds the largest host "
                    "(%d chips); downsizing the serving mesh",
                    chips_per_worker, max_per_service)
                chips_per_worker = max_per_service
            n_replicas = max(1, min(
                n_replicas,
                alloc.total_chips
                // max(len(best_trials) * chips_per_worker, 1)))
        # Fused ensemble (budget ENSEMBLE_FUSED): one worker per replica
        # slot holds ALL best trials co-resident and answers with the
        # final cross-trial ensemble — when the trials share a compiled
        # predict, the whole ensemble is a single vmapped device dispatch
        # (worker/inference.py _FusedEnsembleModel). Deployment shape
        # becomes n_replicas fused workers instead of a fleet per trial.
        fused = bool(budget.get(BudgetType.ENSEMBLE_FUSED, 0))
        if fused and generative:
            # fusing co-locates trials to answer one batch as one unit —
            # meaningless for a single-trial token stream; refuse typed
            # rather than deploy a worker shape the decode loop can't run
            self._db.mark_inference_job_as_errored(inference_job_id)
            raise ServiceDeploymentError(
                "budget ENSEMBLE_FUSED is unsupported for TEXT_GENERATION "
                "jobs: a token stream answers from one model, not a fused "
                "cross-trial ensemble — drop ENSEMBLE_FUSED")
        # Speculative decoding (budget GEN_DRAFT_TRIAL): the named draft
        # trial must exist, be COMPLETED, and be generation-capable — a
        # bad draft is a typed deploy error HERE, never a worker-boot
        # crash that takes the whole serving fleet down with it.
        draft_tid = budget.get(BudgetType.GEN_DRAFT_TRIAL)
        if draft_tid:
            if not generative:
                self._db.mark_inference_job_as_errored(inference_job_id)
                raise ServiceDeploymentError(
                    "budget GEN_DRAFT_TRIAL is only meaningful for "
                    "TEXT_GENERATION jobs — drop it, or deploy a "
                    "generative train job")
            draft_trial = self._db.get_trial(str(draft_tid))
            if draft_trial is None:
                self._db.mark_inference_job_as_errored(inference_job_id)
                raise ServiceDeploymentError(
                    f"budget GEN_DRAFT_TRIAL names unknown trial "
                    f"{draft_tid!r}")
            if draft_trial.get("status") != TrialStatus.COMPLETED:
                self._db.mark_inference_job_as_errored(inference_job_id)
                raise ServiceDeploymentError(
                    f"budget GEN_DRAFT_TRIAL trial {draft_tid!r} is "
                    f"{draft_trial.get('status')}, not COMPLETED — a "
                    "draft model needs trained params to propose tokens")
            draft_model = self._db.get_model(draft_trial["model_id"])
            from rafiki_tpu.admin.admin import Admin

            if draft_model is None \
                    or not Admin._model_generation_capable(draft_model):
                self._db.mark_inference_job_as_errored(inference_job_id)
                raise ServiceDeploymentError(
                    f"budget GEN_DRAFT_TRIAL trial {draft_tid!r} is not "
                    "generation-capable — the draft must implement the "
                    "generation contract (init_kv_cache/prefill/"
                    "decode_step) plus decode_step_sampled")
        if fused:
            from rafiki_tpu.sdk.sandbox import sandbox_enabled

            if sandbox_enabled():
                # ADVICE r5: fused serving would co-locate one JAX
                # sandbox CHILD PROCESS per trial on a single worker's
                # chip grant — N children contending for the same
                # devices is unsupported (and co-residency is the whole
                # point of fusing). Refuse with a typed deploy error
                # instead of failing at worker startup; the per-trial
                # fleet works fine under the sandbox.
                self._db.mark_inference_job_as_errored(inference_job_id)
                raise ServiceDeploymentError(
                    "budget ENSEMBLE_FUSED is unsupported with "
                    "RAFIKI_SANDBOX=1: fused serving co-locates every "
                    "best trial in one worker process, but sandboxed "
                    "models run as separate child processes that would "
                    "contend for the worker's chip grant — drop "
                    "ENSEMBLE_FUSED (per-trial fleet) or disable the "
                    "sandbox for this deployment")
            if alloc is not None:
                n_replicas = max(1, min(
                    config.INFERENCE_WORKER_REPLICAS_PER_TRIAL,
                    alloc.total_chips // max(chips_per_worker, 1)))
            # each deployment unit serves the whole group; the bookkeeping
            # row carries the group's top trial
            units = [{"trial_id": best_trials[0]["id"],
                      "group": f"fused:{inference_job_id}",
                      "trial_ids": [t["id"] for t in best_trials]}
                     for _ in range(n_replicas)]
        else:
            units = [{"trial_id": trial["id"], "group": trial["id"],
                      "trial_ids": None}
                     for trial in best_trials for _ in range(n_replicas)]
        try:
            for unit in units:
                _chaos_deploy(inference_job_id, unit["trial_id"])
                service = self._db.create_service(ServiceType.INFERENCE)
                self._db.create_inference_job_worker(
                    service["id"], inference_job_id, unit["trial_id"]
                )
                worker_trials[service["id"]] = unit["group"]
                worker_cls = InferenceWorker
                if generative:
                    from rafiki_tpu.worker.generation import GenerationWorker

                    worker_cls = GenerationWorker
                worker = worker_cls(
                    inference_job_id, unit["trial_id"], self._db,
                    self._broker, trial_ids=unit["trial_ids"],
                )
                # serving executors prefer an exclusive chip but fall
                # back to shared devices when training holds them all
                try:
                    ctx = self._placement.create_service(
                        service["id"],
                        ServiceType.INFERENCE,
                        worker.start,
                        n_chips=chips_per_worker,
                        best_effort_chips=True,
                        extra={"inference_job_id": inference_job_id,
                               "trial_id": unit["trial_id"],
                               **({"trial_ids": unit["trial_ids"]}
                                  if unit["trial_ids"] else {})},
                    )
                except Exception:
                    # close the row: it was never placed, and rollback
                    # only iterates sids in `created`
                    self._db.mark_service_as_stopped(service["id"])
                    raise
                # in `created` from the moment it is placed, so the
                # outer rollback tears it down even if the chip-index
                # bookkeeping below fails
                created.append(service["id"])
                self._db.update_service_chips(service["id"], ctx.chips)
                # STARTED -> DEPLOYING (guarded) while the deploy wait
                # runs: a row stuck here past SERVICE_DEPLOY_TIMEOUT_S
                # is a wedged deploy, and doctor flags it
                self._db.mark_service_as_deploying(service["id"])
            predictor_service = self._db.create_service(ServiceType.PREDICT)
            self._db.update_inference_job_predictor(
                inference_job_id, predictor_service["id"]
            )
            predictor = Predictor(
                inference_job_id, self._broker, train_job["task"],
                worker_trials=worker_trials,
            )
            with self._lock:
                self._predictors[inference_job_id] = predictor
            if config.PREDICTOR_PORTS:
                # dedicated serving door (reference parity: per-job
                # published ports, reference services_manager.py:379-384)
                from rafiki_tpu.predictor.server import PredictorServer

                psrv = PredictorServer(
                    predictor, train_job["app"],
                    host=config.PREDICTOR_HOST).start()
                with self._lock:
                    self._predict_servers[inference_job_id] = psrv
                self._db.update_service_host_port(
                    predictor_service["id"], psrv.host, psrv.port)
            self._wait_until_services_running(created)
            self._db.mark_service_as_running(predictor_service["id"])
            self._db.mark_inference_job_as_running(inference_job_id)
            return predictor
        except Exception:
            with self._lock:
                self._predictors.pop(inference_job_id, None)
                psrv = self._predict_servers.pop(inference_job_id, None)
            if psrv is not None:
                # failed deploy: nothing admitted is worth draining for —
                # close immediately rather than wait the drain window
                psrv.stop(drain_timeout_s=0.0)
            for sid in created:
                self._destroy_service(sid, wait=False)
            self._db.mark_inference_job_as_errored(inference_job_id)
            raise

    # -- control-plane crash recovery (admin/recovery.py) --------------------

    def adopt_inference_job(self, inference_job_id: str) -> Optional[Predictor]:
        """Rebuild the in-process serving head for an inference job whose
        replicas survived an admin restart: a fresh Predictor over the
        worker queues the recovery pass already re-registered with the
        broker, plus a rebound PredictorServer when the deployment uses
        per-job ports. predict() then answers WITHOUT a redeploy; the
        predict-route cache repopulates lazily on first use."""
        inf = self._db.get_inference_job(inference_job_id)
        if inf is None:
            return None
        train_job = self._db.get_train_job(inf["train_job_id"])
        if train_job is None:
            return None
        budget = inf.get("budget") or {}
        fused = bool(budget.get(BudgetType.ENSEMBLE_FUSED, 0))
        group = f"fused:{inference_job_id}" if fused else None
        workers = self._db.get_workers_of_inference_job(inference_job_id)
        # standbys adopt like any replica (their processes were re-owned
        # or fenced by the recovery pass) but stay OUT of the routable
        # set: promotion, not adoption, is what makes a standby serve
        worker_trials = {
            w["service_id"]: (group or w["trial_id"]) for w in workers
            if not int(w.get("standby") or 0)
        }
        # recovery adoption invalidates the job's prediction cache: the
        # adopted fleet may differ from what the dead admin last served
        # (a rollout resolved at boot, replicas lost), and a pre-crash
        # answer must never outlive the reconcile (in practice the cache
        # died with the old process — this guards the same-process
        # adoption paths tests and retries exercise). The rebuilt
        # Predictor carries the adopted fleet's real rollout generation
        # so cache keys stay version-true.
        from rafiki_tpu.predictor.result_cache import get_cache

        get_cache().flush_job(inference_job_id, reason="adoption")
        version = max((int(w.get("model_version") or 0) for w in workers),
                      default=0)
        predictor = Predictor(
            inference_job_id, self._broker, train_job["task"],
            worker_trials=worker_trials, serving_version=version,
        )
        with self._lock:
            self._predictors[inference_job_id] = predictor
            # idempotency: recovery retries this method on transient
            # store faults — a server bound by an earlier attempt must be
            # closed, not leaked as a stale listener
            stale_psrv = self._predict_servers.pop(inference_job_id, None)
        if stale_psrv is not None:
            stale_psrv.stop(drain_timeout_s=0.0)
        psid = inf.get("predictor_service_id")
        if config.PREDICTOR_PORTS:
            from rafiki_tpu.predictor.server import PredictorServer

            psrv = PredictorServer(
                predictor, train_job["app"],
                host=config.PREDICTOR_HOST).start()
            with self._lock:
                self._predict_servers[inference_job_id] = psrv
            if psid:
                # the dedicated door moved with the new admin process:
                # republish its host:port
                self._db.update_service_host_port(psid, psrv.host, psrv.port)
        if psid:
            # the predictor head lives again — in THIS process
            self._db.mark_service_as_running(psid)
        self._db.mark_inference_job_as_running(inference_job_id)
        return predictor

    def restart_train_worker(self, service_id: str, sub_train_job_id: str,
                             n_chips: int = 0) -> bool:
        """Relaunch a train executor under its EXISTING service id after
        a control-plane restart on a single-host placement (the executor
        threads died with the old admin process). The stale-RUNNING-trial
        resume in worker/train.py then re-runs exactly the trials the
        dead executor left behind. Best-effort chips: a busy grant must
        downgrade the executor, not error the job a second time."""
        worker = TrainWorker(
            sub_train_job_id,
            self._db,
            self._advisors,
            send_event=self._send_event,
            params_dir=self._params_dir,
        )
        try:
            ctx = self._placement.create_service(
                service_id, ServiceType.TRAIN, worker.start,
                n_chips=n_chips,
                best_effort_chips=True,
                extra={"sub_train_job_id": sub_train_job_id},
            )
        except Exception:
            logger.exception("restarting train worker %s failed",
                             service_id[:8])
            return False
        try:
            self._db.update_service_chips(service_id, ctx.chips)
        except Exception:
            logger.exception("chip bookkeeping failed for restarted %s",
                             service_id[:8])
        return True

    def get_predictor(self, inference_job_id: str) -> Optional[Predictor]:
        with self._lock:
            return self._predictors.get(inference_job_id)

    def predictors(self) -> Dict[str, Predictor]:
        """Snapshot of the live {inference_job_id: Predictor} map (fleet
        health reads every job's queue depths / overload counters)."""
        with self._lock:
            return dict(self._predictors)

    def stop_inference_services(self, inference_job_id: str) -> None:
        for w in self._db.get_workers_of_inference_job(inference_job_id):
            self._destroy_service(w["service_id"], wait=False)
        self._teardown_serving(inference_job_id, errored=False)

    # -- elastic serving (admin/autoscaler.py; docs/failure-model.md
    # "Overload adaptation") ------------------------------------------------

    def live_inference_workers(self, inference_job_id: str) -> List[Dict]:
        """The job's live serving replicas: worker rows whose service is
        non-terminal, annotated with the predictor's replica-group key
        (trial id, or the fused group). Drain-in-progress replicas are
        excluded — they no longer take traffic — and so are warm
        standbys, which never took any (admin/warm_pool.py)."""
        inf = self._db.get_inference_job(inference_job_id)
        fused = bool(((inf or {}).get("budget") or {}).get(
            BudgetType.ENSEMBLE_FUSED, 0))
        group_of = (lambda t: f"fused:{inference_job_id}") if fused \
            else (lambda t: t)
        with self._scale_lock:
            draining = set(self._scale_draining)
        # one status-filtered query (idx_service_status), not a
        # get_service round trip per worker row — this runs every
        # autoscaler tick for every job
        alive = {
            s["id"]: s
            for s in self._db.get_services(statuses=[
                ServiceStatus.STARTED, ServiceStatus.DEPLOYING,
                ServiceStatus.RUNNING])}
        out: List[Dict] = []
        for w in self._db.get_workers_of_inference_job(inference_job_id):
            if w["service_id"] in draining or int(w.get("standby") or 0):
                continue
            svc = alive.get(w["service_id"])
            if svc is not None:
                out.append({"service_id": w["service_id"],
                            "trial_id": w["trial_id"],
                            "group": group_of(w["trial_id"]),
                            # rollout generation this replica serves
                            # (admin/rollout.py; 0 = initial deploy)
                            "model_version": int(
                                w.get("model_version") or 0),
                            "chips": svc.get("chips") or []})
        return out

    def scale_inference_job(self, inference_job_id: str, delta: int,
                            borrow: bool = True,
                            drain_timeout_s: Optional[float] = None,
                            min_replicas: int = 1) -> Dict[str, Any]:
        """Add (``delta`` > 0) or gracefully drain (``delta`` < 0) serving
        replicas of a RUNNING inference job WITHOUT a redeploy — the live
        elasticity primitive under the autoscaler and the operator scale
        API. Returns {added, removed, borrowed_chips, returned_chips}.

        Scale-up places each new replica best-effort: with an exclusive
        chip grant when ``borrow`` is allowed by the chip arbiter (the
        loan is recorded for training to reclaim), on shared devices
        otherwise. Scale-down picks borrowed replicas first, never drops
        a trial's last replica while other trials keep several, and never
        goes below ``min_replicas`` live replicas job-wide."""
        inf = self._db.get_inference_job(inference_job_id)
        if inf is None or inf["status"] != InferenceJobStatus.RUNNING:
            raise ServiceDeploymentError(
                f"inference job {inference_job_id} is not RUNNING")
        predictor = self.get_predictor(inference_job_id)
        if predictor is None:
            raise ServiceDeploymentError(
                f"inference job {inference_job_id} has no live predictor")
        report: Dict[str, Any] = {"added": [], "removed": [], "promoted": [],
                                  "borrowed_chips": 0, "returned_chips": 0}
        if delta > 0:
            for _ in range(delta):
                # per-replica isolation mirroring the drain path: a later
                # failure must not erase the record of replicas (and chip
                # loans) that DID land
                try:
                    sid, borrowed, promoted = self._scale_up_one(
                        inference_job_id, inf, predictor, borrow)
                except Exception as e:
                    if not report["added"]:
                        raise
                    logger.exception(
                        "scale-up of job %s stopped after %d replica(s)",
                        inference_job_id[:8], len(report["added"]))
                    report["error"] = str(e)
                    break
                report["added"].append(sid)
                if promoted:
                    report["promoted"].append(sid)
                report["borrowed_chips"] += borrowed
        elif delta < 0:
            victims = self._pick_scale_down_victims(
                inference_job_id, -delta, min_replicas)
            freed, removed = self.drain_replicas(
                inference_job_id, victims, drain_timeout_s=drain_timeout_s)
            report["removed"] = removed
            report["returned_chips"] = freed
        return report

    def _scale_up_one(self, inference_job_id: str, inf: Dict,
                      predictor, borrow: bool):
        """Add ONE serving replica: promote a warm standby when the pool
        holds one (an ``add_worker`` route, ~ms — the replica is already
        loaded, warmed, and holding its chips), else place a fresh
        replica for the trial group that currently has the fewest live
        replicas. Returns (service_id, borrowed_chip_count,
        served_by_promotion)."""
        promoted = self.promote_standby(inference_job_id)
        if promoted is not None:
            return promoted, 0, True
        sid, borrowed, group, chips = self._place_replica(
            inference_job_id, inf, borrow=borrow, standby=False)
        # replica JOIN: route new requests to it (its queue is already
        # registered with the broker by the worker's startup)
        predictor.add_worker(sid, group)
        logger.info("scaled UP job %s: replica %s for group %s "
                    "(chips=%s)", inference_job_id[:8], sid[:8],
                    group[:16], chips)
        return sid, borrowed, False

    def _place_replica(self, inference_job_id: str, inf: Dict,
                       borrow: bool, standby: bool):
        """Deploy ONE extra serving replica for the trial group that
        currently has the fewest live replicas (the scale-up placement
        body, shared with the warm pool). ``standby`` marks the worker
        row: the replica loads and pre-warms exactly like a routable one
        but is NOT handed to the predictor — promotion does that later.
        Returns (service_id, borrowed_chip_count, group, chips)."""
        train_job = self._db.get_train_job(inf["train_job_id"])
        assert train_job is not None
        budget = inf.get("budget") or {}
        fused = bool(budget.get(BudgetType.ENSEMBLE_FUSED, 0))
        chips_per_worker = max(
            int(budget.get(BudgetType.CHIPS_PER_WORKER, 1)), 1)
        alloc = getattr(self._placement, "allocator", None)
        if alloc is not None:
            max_per_service = getattr(
                alloc, "max_chips_per_service", alloc.total_chips)
            if chips_per_worker > max_per_service > 0:
                chips_per_worker = max_per_service
        live = self.live_inference_workers(inference_job_id)
        if fused:
            best = self._db.get_best_trials_of_train_job(
                train_job["id"], max_count=config.INFERENCE_MAX_BEST_TRIALS)
            unit = {"trial_id": best[0]["id"] if best
                    else (live[0]["trial_id"] if live else None),
                    "group": f"fused:{inference_job_id}",
                    "trial_ids": [t["id"] for t in best] or None}
        else:
            by_group: Dict[str, int] = {}
            for w in live:
                by_group[w["group"]] = by_group.get(w["group"], 0) + 1
            if not by_group:
                raise ServiceDeploymentError(
                    f"inference job {inference_job_id} has no live "
                    "replicas to model the new one on")
            group = min(sorted(by_group), key=lambda g: by_group[g])
            unit = {"trial_id": group, "group": group, "trial_ids": None}
        if unit["trial_id"] is None:
            raise ServiceDeploymentError(
                f"no trial to serve for job {inference_job_id}")
        # a scaled-up replica inherits its group's rollout generation —
        # a post-rollout scale-up must not mint version-0 rows beside
        # version-N siblings (recovery reads the version to reconstruct
        # a mid-rollout fleet)
        version = max((w["model_version"] for w in live
                       if fused or w["group"] == unit["group"]), default=0)
        _chaos_deploy(inference_job_id, unit["trial_id"])
        # chip loan: exclusive grant only when the arbiter allows it (the
        # training floor stays intact); otherwise shared devices.
        # begin_borrow is an atomic check-AND-reserve so two concurrent
        # scale-ups can't both pass the floor check before either takes
        # its chips from the allocator
        want_chips = 0
        reservation = None
        if borrow and self._arbiter is not None:
            reservation = self._arbiter.begin_borrow(chips_per_worker)
            if reservation is not None:
                want_chips = chips_per_worker
        try:
            service = self._db.create_service(ServiceType.INFERENCE)
            self._db.create_inference_job_worker(
                service["id"], inference_job_id, unit["trial_id"],
                model_version=version, standby=standby)
            worker_cls = InferenceWorker
            if train_job["task"] == TaskType.TEXT_GENERATION:
                from rafiki_tpu.worker.generation import GenerationWorker

                worker_cls = GenerationWorker
            worker = worker_cls(
                inference_job_id, unit["trial_id"], self._db, self._broker,
                trial_ids=unit["trial_ids"],
            )
            try:
                ctx = self._placement.create_service(
                    service["id"], ServiceType.INFERENCE, worker.start,
                    n_chips=want_chips, best_effort_chips=True,
                    extra={"inference_job_id": inference_job_id,
                           "trial_id": unit["trial_id"],
                           **({"trial_ids": unit["trial_ids"]}
                              if unit["trial_ids"] else {})},
                )
            except Exception:
                self._db.mark_service_as_stopped(service["id"])
                raise
            try:
                self._db.update_service_chips(service["id"], ctx.chips)
                self._db.mark_service_as_deploying(service["id"])
                self._wait_until_services_running([service["id"]])
            except Exception:
                self._destroy_service(service["id"], wait=False)
                raise
        except Exception:
            if reservation is not None:
                self._arbiter.cancel_borrow(reservation)
            raise
        borrowed = 0
        if reservation is not None:
            if want_chips and ctx.chips:
                self._arbiter.commit_borrow(
                    reservation, service["id"], inference_job_id, ctx.chips)
                borrowed = len(ctx.chips)
                # durable twin of the in-memory loan book: a successor
                # admin rebuilds the arbiter from this column when it
                # adopts the replica (admin/recovery.py
                # _readopt_chip_loan) — without it, an admin restart
                # silently leaked the loan until the replica stopped
                try:
                    self._db.set_worker_borrowed_chips(
                        service["id"], borrowed)
                # lint: absorb(the marker is recovery accounting: failing to write it must not undo a committed scale-up)
                except Exception:
                    logger.exception(
                        "could not persist the %d-chip loan marker for "
                        "replica %s", borrowed, service["id"][:8])
            else:
                self._arbiter.cancel_borrow(reservation)
        return service["id"], borrowed, unit["group"], ctx.chips

    # -- warm standby pool (admin/warm_pool.py; docs/failure-model.md
    # "Cold-start faults") ---------------------------------------------------

    def standby_workers(self, inference_job_id: str) -> List[Dict]:
        """The job's warm standbys: standby-flagged worker rows whose
        service is RUNNING (loaded + pre-warmed, holding chips, NOT
        routed). DEPLOYING standbys are still warming and not yet
        promotable."""
        inf = self._db.get_inference_job(inference_job_id)
        fused = bool(((inf or {}).get("budget") or {}).get(
            BudgetType.ENSEMBLE_FUSED, 0))
        group_of = (lambda t: f"fused:{inference_job_id}") if fused \
            else (lambda t: t)
        alive = {
            s["id"]: s
            for s in self._db.get_services(statuses=[ServiceStatus.RUNNING])}
        out: List[Dict] = []
        for w in self._db.get_workers_of_inference_job(inference_job_id):
            if not int(w.get("standby") or 0):
                continue
            svc = alive.get(w["service_id"])
            if svc is not None:
                out.append({"service_id": w["service_id"],
                            "trial_id": w["trial_id"],
                            "group": group_of(w["trial_id"]),
                            "model_version": int(
                                w.get("model_version") or 0),
                            "chips": svc.get("chips") or []})
        return out

    def create_standby_replica(self, inference_job_id: str) -> str:
        """Place ONE warm standby for a RUNNING inference job: loaded,
        pre-warmed, chips held through the arbiter's borrow book
        (training's reclaim drains standbys FIRST), but never routed —
        promotion is what makes it serve. Returns the service id."""
        inf = self._db.get_inference_job(inference_job_id)
        if inf is None or inf["status"] != InferenceJobStatus.RUNNING:
            raise ServiceDeploymentError(
                f"inference job {inference_job_id} is not RUNNING")
        sid, borrowed, group, chips = self._place_replica(
            inference_job_id, inf, borrow=True, standby=True)
        if borrowed and self._arbiter is not None:
            # reclaim-priority tag: training wins these chips back FIRST
            self._arbiter.mark_standby(sid, True)
        logger.info(
            "warm pool: standby %s ready for job %s group %s (chips=%s,"
            " borrowed=%d)", sid[:8], inference_job_id[:8], group[:16],
            chips, borrowed)
        return sid

    def promote_standby(self, inference_job_id: str,
                        group: Optional[str] = None) -> Optional[str]:
        """Turn one warm standby into a routable replica: clear the
        durable standby flag, then ``predictor.add_worker`` — the ~ms
        scale-up/replacement path (no deploy, no compile; the worker's
        queue has been registered since its boot). Standbys older than
        what their group currently serves are skipped (rollouts retire
        those — a promotion must never resurrect a stale version).
        Returns the promoted service id, or None when the pool is empty
        for the (optional) group filter."""
        predictor = self.get_predictor(inference_job_id)
        if predictor is None:
            return None
        candidates = self.standby_workers(inference_job_id)
        if group is not None:
            candidates = [w for w in candidates if w["group"] == group]
        cur: Dict[str, int] = {}
        for w in self.live_inference_workers(inference_job_id):
            cur[w["group"]] = max(cur.get(w["group"], 0),
                                  w["model_version"])
        for w in candidates:
            if w["model_version"] < cur.get(w["group"], 0):
                continue
            sid = w["service_id"]
            try:
                # flag first: a crash between the two leaves a
                # promotable-but-unrouted replica (re-promoted or swept),
                # never a routed row recovery would treat as a standby
                self._db.set_worker_standby(sid, False)
                predictor.add_worker(sid, w["group"])
            # lint: absorb(a single unpromotable standby must not block trying its siblings; the pool loop replaces it)
            except Exception:
                logger.exception("promoting standby %s failed; trying "
                                 "siblings", sid[:8])
                continue
            if self._arbiter is not None:
                # now a load-bearing replica: reclaim treats its loan
                # like any other serving replica's
                self._arbiter.mark_standby(sid, False)
            from rafiki_tpu.utils.metrics import REGISTRY

            REGISTRY.counter(
                "rafiki_warm_pool_promotions_total",
                "warm standbys promoted into serving").inc()
            logger.info("warm pool: promoted standby %s into job %s "
                        "group %s", sid[:8], inference_job_id[:8],
                        w["group"][:16])
            return sid
        return None

    def drop_standby(self, service_id: str) -> None:
        """Destroy a standby outright (stale-version retirement, pool
        shrink): it serves no traffic, so there is nothing to drain —
        its chip loan comes home through the _destroy_service
        note_return chokepoint."""
        self._destroy_service(service_id, wait=False)

    # -- safe live rollouts (admin/rollout.py; docs/failure-model.md
    # "Rollout faults") ------------------------------------------------------

    def deploy_version_replica(self, inference_job_id: str, trial_id: str,
                               model_version: int) -> str:
        """Place ONE serving replica of ``trial_id`` carrying
        ``model_version`` on its worker row — the rollout controller's
        canary/rolling/restore placement primitive. Same placement shape
        as the initial deploy (prefers an exclusive chip, falls back to
        shared devices); no chip-arbiter loan — a rollout replaces
        capacity, it does not grow it. Raises ServiceDeploymentError on
        placement failure, deploy timeout, or a chaos ``site=deploy``
        injection; a failed replica is fully torn down before the raise
        so the caller's rollback never inherits half-placed state."""
        inf = self._db.get_inference_job(inference_job_id)
        if inf is None:
            raise ServiceDeploymentError(
                f"no inference job {inference_job_id}")
        train_job = self._db.get_train_job(inf["train_job_id"])
        assert train_job is not None
        budget = inf.get("budget") or {}
        chips_per_worker = max(
            int(budget.get(BudgetType.CHIPS_PER_WORKER, 1)), 1)
        alloc = getattr(self._placement, "allocator", None)
        if alloc is not None:
            max_per_service = getattr(
                alloc, "max_chips_per_service", alloc.total_chips)
            if chips_per_worker > max_per_service > 0:
                chips_per_worker = max_per_service
        _chaos_deploy(inference_job_id, trial_id)
        service = self._db.create_service(ServiceType.INFERENCE)
        self._db.create_inference_job_worker(
            service["id"], inference_job_id, trial_id,
            model_version=model_version)
        worker_cls = InferenceWorker
        if train_job["task"] == TaskType.TEXT_GENERATION:
            from rafiki_tpu.worker.generation import GenerationWorker

            worker_cls = GenerationWorker
        worker = worker_cls(
            inference_job_id, trial_id, self._db, self._broker)
        try:
            ctx = self._placement.create_service(
                service["id"], ServiceType.INFERENCE, worker.start,
                n_chips=chips_per_worker, best_effort_chips=True,
                extra={"inference_job_id": inference_job_id,
                       "trial_id": trial_id},
            )
        except Exception as e:
            self._db.mark_service_as_stopped(service["id"])
            raise ServiceDeploymentError(
                f"placing replica of trial {trial_id} failed: "
                f"{type(e).__name__}: {e}") from e
        try:
            self._db.update_service_chips(service["id"], ctx.chips)
            self._db.mark_service_as_deploying(service["id"])
            self._wait_until_services_running([service["id"]])
        except Exception as e:
            self._destroy_service(service["id"], wait=False)
            if isinstance(e, ServiceDeploymentError):
                raise
            raise ServiceDeploymentError(
                f"replica of trial {trial_id} never reached RUNNING: "
                f"{type(e).__name__}: {e}") from e
        logger.info("rollout: placed replica %s (trial %s, version %d) "
                    "for job %s", service["id"][:8], trial_id[:8],
                    model_version, inference_job_id[:8])
        return service["id"]

    def _pick_scale_down_victims(self, inference_job_id: str, n: int,
                                 min_replicas: int) -> List[str]:
        """Choose up to ``n`` replicas to drain: borrowed-chip replicas
        first (scale-down returns the loan), then the youngest rows; a
        trial's LAST replica is only eligible when every other trial is
        down to one as well (the ensemble must not silently lose a trial
        while siblings hold spares), and the job never drops below
        ``min_replicas`` live replicas."""
        live = self.live_inference_workers(inference_job_id)
        headroom = len(live) - max(min_replicas, 1)
        if headroom <= 0:
            return []
        n = min(n, headroom)
        by_group: Dict[str, int] = {}
        for w in live:
            by_group[w["group"]] = by_group.get(w["group"], 0) + 1
        borrowed = set()
        if self._arbiter is not None:
            borrowed = set(self._arbiter.borrowed())
        # youngest-last rows come back last from the store scan; prefer
        # draining the replicas added most recently
        ordered = sorted(
            reversed(live),
            key=lambda w: 0 if w["service_id"] in borrowed else 1)
        victims: List[str] = []
        for w in ordered:
            if len(victims) >= n:
                break
            spare_groups = any(
                c > 1 for g, c in by_group.items() if g != w["group"])
            if by_group[w["group"]] <= 1 and spare_groups:
                continue
            victims.append(w["service_id"])
            by_group[w["group"]] -= 1
        return victims

    def drain_replicas(
            self, inference_job_id: str, service_ids: List[str],
            drain_timeout_s: Optional[float] = None,
    ) -> "tuple[int, List[str]]":
        """Gracefully remove serving replicas: stop admitting (the
        predictor retires the replica from its fan-out), flush the worker
        queue (bounded by ``RAFIKI_AUTOSCALE_DRAIN_S``), then destroy —
        zero in-flight requests dropped on the happy path, and any
        straggler that races the final close is re-routed by the
        predictor's failover machinery. Idempotent: replicas already
        draining (a second concurrent scale-down) are skipped. Returns
        ``(borrowed chips returned to the pool, service_ids actually
        removed)`` — a victim whose drain failed is restored to the
        fan-out and does NOT count as removed."""
        if drain_timeout_s is None:
            drain_timeout_s = float(config.AUTOSCALE_DRAIN_S)
        with self._scale_lock:
            mine = [s for s in service_ids if s not in self._scale_draining]
            self._scale_draining.update(mine)
        predictor = self.get_predictor(inference_job_id)
        freed = 0
        removed: List[str] = []
        try:
            for sid in mine:
                if predictor is not None:
                    predictor.retire_worker(sid)
            for sid in mine:
                # per-victim isolation: one failed drain must not abandon
                # the OTHER victims retired-but-undestroyed (dead capacity
                # still counted live, loans never returned)
                loan = 0
                if self._arbiter is not None:
                    # read the loan size up front: _destroy_service (the
                    # teardown chokepoint inside _drain_one) performs the
                    # actual note_return
                    loan = self._arbiter.borrowed().get(sid, ("", 0))[1]
                try:
                    self._drain_one(inference_job_id, sid, predictor,
                                    drain_timeout_s)
                except Exception:
                    logger.exception(
                        "drain of replica %s failed; restoring it to the "
                        "fan-out", sid[:8])
                    if predictor is not None:
                        predictor.unretire_worker(sid)
                    continue
                removed.append(sid)
                freed += loan
        finally:
            with self._scale_lock:
                self._scale_draining.difference_update(mine)
        return freed, removed

    @staticmethod
    def _resident_streams(sid: str) -> int:
        """Generation streams still RESIDENT on a replica (busy slots +
        preempted-stashed) — what a drain must wait out beyond the queue
        depth: a generation replica with an empty inbox can still be
        minutes from finishing its admitted streams. 0 for
        classification replicas (no such stats row key)."""
        from rafiki_tpu.worker.inference import SERVING_STATS, _stats_lock

        with _stats_lock:
            row = SERVING_STATS.get(sid)
            return int(row.get("gen_resident_streams", 0)) if row else 0

    def _drain_one(self, inference_job_id: str, sid: str, predictor,
                   drain_timeout_s: float) -> None:
        queue = self._broker.get_worker_queues(inference_job_id).get(sid)
        depth_fn = getattr(queue, "depth", None)
        deadline = time.monotonic() + max(drain_timeout_s, 0.0)
        zero_reads = 0
        while callable(depth_fn) and time.monotonic() < deadline:
            try:
                depth = depth_fn()
            # lint: absorb(a dead queue handle simply ends the drain wait)
            except Exception:
                break
            if depth <= 0 and self._resident_streams(sid) <= 0:
                # consecutive-zero confirmation: a request that snapshotted
                # its routes before the retire may still land one submit —
                # give those stragglers a beat to either arrive or finish
                zero_reads += 1
                if zero_reads >= 3:
                    break
            else:
                zero_reads = 0
            time.sleep(0.03)
        else:
            if callable(depth_fn):
                try:
                    leftover = depth_fn()
                # lint: absorb(final depth read is diagnostic only)
                except Exception:
                    leftover = -1
                if leftover:
                    logger.warning(
                        "replica %s still has %d queued queries after the "
                        "%.1fs drain window; destroying anyway (stragglers "
                        "fail over to siblings)", sid[:8], leftover,
                        drain_timeout_s)
        # wait=True: the worker finishes its in-flight batch before the
        # queue closes, so everything taken is answered
        self._destroy_service(sid, wait=True)
        if predictor is not None:
            predictor.drop_worker(sid)
        logger.info("scaled DOWN job %s: replica %s drained and destroyed",
                    inference_job_id[:8], sid[:8])

    def reclaim_borrowed(self, n_chips: int) -> int:
        """Chip-arbiter reclaim callback: drain borrowed serving replicas
        until ``n_chips`` came home or the loan book is empty. Training
        demand outranks borrowed serving capacity by contract — but a
        reclaim is still a scale-down, so it honors the same guards as
        any other: never below the job's replica floor, never a trial's
        last replica while siblings hold spares (a borrowed replica may
        have BECOME load-bearing if its siblings died since the loan).

        Warm standbys drain FIRST: they serve no traffic, so their
        chips come home with an outright destroy (no drain window, no
        routing guards) before any routable replica is touched —
        the training floor outranks warm spare capacity by contract."""
        if self._arbiter is None:
            return 0
        freed = 0
        for sid, (job_id, n) in list(self._arbiter.borrowed().items()):
            if freed >= n_chips:
                break
            try:
                row = self._db.get_inference_job_worker(sid)
            # lint: absorb(an unreadable worker row just means this loan is reclaimed through the regular drain path below)
            except Exception:
                continue
            if row is not None and int(row.get("standby") or 0):
                self._destroy_service(sid, wait=False)
                freed += n
                from rafiki_tpu.utils.metrics import REGISTRY

                REGISTRY.counter(
                    "rafiki_warm_pool_reclaims_total",
                    "warm standbys destroyed to return chips to "
                    "training").inc()
                logger.info("reclaim: standby %s destroyed, %d chip(s) "
                            "home", sid[:8], n)
        if freed >= n_chips:
            return freed
        loans = self._arbiter.borrowed()
        by_job: Dict[str, List[str]] = {}
        for sid, (job_id, _) in loans.items():
            by_job.setdefault(job_id, []).append(sid)
        min_r = max(int(config.AUTOSCALE_MIN_REPLICAS), 1)
        for job_id, sids in by_job.items():
            if freed >= n_chips:
                break
            try:
                eligible = [
                    s for s in self._pick_scale_down_victims(
                        job_id, len(sids), min_r)
                    if s in loans]
            except Exception:
                logger.exception("reclaim victim pick for job %s failed",
                                 job_id[:8])
                continue
            for sid in eligible:
                if freed >= n_chips:
                    break
                try:
                    freed += self.drain_replicas(job_id, [sid])[0]
                except Exception:
                    logger.exception("reclaim drain of %s failed", sid[:8])
        return freed

    # -- shared --------------------------------------------------------------

    def _destroy_service(self, service_id: str, wait: bool = True) -> None:
        try:
            self._placement.destroy_service(service_id, wait=wait)
        except Exception:
            logger.exception("destroying service %s failed", service_id)
        self._db.mark_service_as_stopped(service_id)
        # every teardown path funnels here: a destroyed replica's chip
        # loan comes home no matter WHY it died (job stop, deploy
        # rollback, drain) — note_return is an idempotent pop. The
        # durable marker clears with it so a later admin restart cannot
        # resurrect a loan that already came home.
        if self._arbiter is not None:
            if self._arbiter.note_return(service_id) > 0:
                try:
                    self._db.set_worker_borrowed_chips(service_id, 0)
                # lint: absorb(the marker is recovery accounting: a failed clear leaves a stale row for a stopped replica, which adoption ignores)
                except Exception:
                    logger.exception(
                        "could not clear the loan marker for replica %s",
                        service_id[:8])

    def _wait_until_services_running(self, service_ids: List[str]) -> None:
        """Poll the store until all services are RUNNING (reference :279-290)."""
        deadline = time.time() + config.SERVICE_DEPLOY_TIMEOUT_S
        pending = set(service_ids)
        while pending:
            for sid in list(pending):
                svc = self._db.get_service(sid)
                if svc is None or svc["status"] == ServiceStatus.ERRORED:
                    raise ServiceDeploymentError(f"Service {sid} errored on deploy")
                if svc["status"] in (ServiceStatus.RUNNING, ServiceStatus.STOPPED):
                    # STOPPED is fine: a fast worker may have already finished
                    pending.discard(sid)
            if pending:
                if time.time() > deadline:
                    raise ServiceDeploymentError(
                        f"Services not running after "
                        f"{config.SERVICE_DEPLOY_TIMEOUT_S}s: {pending}"
                    )
                time.sleep(0.05)
