"""Drift closed loop: detection → bounded auto-retrain → SLO-guarded
auto-rollout (docs/failure-model.md "Model drift faults").

The reference platform trains and serves but never closes the loop — a
model that goes stale serves stale answers until a human notices. This
controller watches each RUNNING inference job's serving plane through
the predictor's drift tap (one (wall_ts, canonical digest, top
probability) sample per served query), compares a trailing window
against a frozen post-rollout baseline, and on a drift verdict launches
exactly ONE warm-started retrain (the incumbent's scored + infeasible
trial history replayed into the new advisor) bounded by
``RAFIKI_DRIFT_RETRAIN_BUDGET`` trials. A better-scoring candidate
auto-rolls-out through the SLO-judged rollout controller (canary →
rolling → done, automatic rollback on breach); any non-success pushes
the loop into an exponentially backed-off cooldown, never a
retrain/rollback flap.

Shape mirrors the autoscaler (admin/autoscaler.py): the instance always
exists — ``GET /fleet/health`` carries its section, the drift
status/ack API goes through it — but the loop thread only runs with
``RAFIKI_DRIFT=1``. Unlike the autoscaler, loop state is durable: one
``drift_state`` row per job (phase, frozen baseline, active retrain job
id, cooldown deadline, rollback streak) so a restarted admin resumes a
mid-loop state without double-launching retrains or stranding a
candidate — the persisted ``retrain_job_id`` is the idempotency key,
and a crash inside the launch itself leaves a write-ahead RETRAINING
intent the recovery hook resolves by adoption or by parking, never by
relaunching.

Degradation contract (drillable via ``RAFIKI_CHAOS site=drift``): a
broken monitor tick is absorbed per job and never touches serving; a
failed retrain launch retries once per tick, bounded by
``RAFIKI_DRIFT_LAUNCH_RETRY_MAX``, then parks with a typed event and
waits for an operator ack.
"""

from __future__ import annotations

import collections
import logging
import threading
import time
from typing import Any, Dict, List, Optional

from rafiki_tpu import config
from rafiki_tpu.constants import (
    BudgetType,
    DriftPhase,
    InferenceJobStatus,
    RolloutPhase,
    TrainJobStatus,
)
from rafiki_tpu.utils import chaos
from rafiki_tpu.utils.metrics import REGISTRY

logger = logging.getLogger(__name__)

#: exponential rollback backoff cap: cooldown * 2**min(streak-1, CAP)
_BACKOFF_CAP = 4
#: distinct digests kept in a frozen baseline population
_BASELINE_DIGESTS = 2048
#: events kept on each persisted drift row (the global deque keeps 100)
_ROW_EVENTS = 40


class DriftMonitorError(RuntimeError):
    """Chaos-injected monitor failure (RAFIKI_CHAOS site=drift, target
    ``tick/<job>``) — absorbed per job; serving is never touched."""


class DriftLaunchError(RuntimeError):
    """Chaos-injected retrain-launch failure (site=drift, target
    ``launch/<job>``) — retried bounded, then the loop parks."""


class DriftController:
    """The closed loop. Public entry points: :meth:`tick` (synchronous,
    also what the loop thread calls), :meth:`status`/:meth:`ack` (the
    HTTP drift routes), :meth:`report` (GET /fleet/health "drift"), and
    :meth:`recover_on_boot` (ControlPlaneRecovery)."""

    def __init__(self, admin) -> None:
        self._admin = admin
        self._services = admin.services
        self._db = admin.db
        self._rollouts = admin.rollouts
        self._closed = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._lock = threading.Lock()
        # per-job mirror of the drift_state row plus volatile bits
        # (launch_attempts, the live signal snapshot)
        self._jobs: Dict[str, Dict[str, Any]] = {}  # guarded-by: _lock
        self.events: collections.deque = collections.deque(
            maxlen=100)  # guarded-by: _lock
        self._m_ticks = REGISTRY.counter(
            "rafiki_drift_ticks_total", "drift monitor ticks")
        self._m_events = REGISTRY.counter(
            "rafiki_drift_events_total",
            "drift verdicts raised by the monitor", ("job",))
        self._m_retrains = REGISTRY.counter(
            "rafiki_drift_retrains_total",
            "auto-retrains launched by the drift loop", ("job",))
        self._m_rollouts = REGISTRY.counter(
            "rafiki_drift_rollouts_total",
            "auto-rollouts completed (candidate serving)", ("job",))
        self._m_rollbacks = REGISTRY.counter(
            "rafiki_drift_rollbacks_total",
            "auto-rollout candidates rolled back by the SLO judge",
            ("job",))
        self._m_parked = REGISTRY.counter(
            "rafiki_drift_parked_total",
            "drift loops parked pending operator ack", ("job",))

    # -- lifecycle (autoscaler-shaped) --------------------------------------

    @property
    def running(self) -> bool:
        t = self._thread
        return bool(t and t.is_alive())

    def start(self) -> "DriftController":
        if self.running:
            return self
        self._closed.clear()
        self._thread = threading.Thread(
            target=self._loop, name="drift", daemon=True)
        self._thread.start()
        return self

    def stop(self) -> None:
        self._closed.set()
        t = self._thread
        if t is not None:
            # the join must outlast a tick's chaos delays + store retries
            t.join(timeout=float(config.DRIFT_INTERVAL_S) + 30)
        self._thread = None

    def _loop(self) -> None:
        while not self._closed.wait(float(config.DRIFT_INTERVAL_S)):
            try:
                self.tick()
            # lint: absorb(the loop thread must survive any tick failure; each tick retries from scratch)
            except Exception:
                logger.exception("drift tick failed")

    # -- the tick -----------------------------------------------------------

    def tick(self) -> List[Dict[str, Any]]:
        """One monitor pass over every live predictor. Synchronous and
        side-effect-complete, so tests (and operators via the loop
        thread) drive the whole state machine through repeated calls."""
        self._m_ticks.inc()
        if self._admin.recovery_status().get("state") == "recovering":
            # boot reconciliation owns mid-loop state until it finishes
            # (recover_on_boot resolves write-ahead intents; ticking
            # before that could double-launch a retrain)
            return []
        actions: List[Dict[str, Any]] = []
        predictors = self._services.predictors()
        with self._lock:
            # drop in-memory state for jobs that stopped serving (their
            # durable row stays for forensics)
            for job_id in list(self._jobs):
                if job_id not in predictors:
                    del self._jobs[job_id]
        for job_id, predictor in predictors.items():
            if self._closed.is_set():
                break
            try:
                self._chaos_tick(job_id)
                action = self._tick_job(job_id, predictor)
            # lint: absorb(degradation contract: a broken monitor tick is logged and skipped — it never touches serving)
            except Exception:
                logger.exception("drift tick failed for job %s", job_id)
                continue
            if action is not None:
                actions.append(action)
        return actions

    @staticmethod
    def _chaos_tick(job_id: str) -> None:
        rule = chaos.hit(chaos.SITE_DRIFT, f"tick/{job_id}")
        if rule is None:
            return
        if rule.action == chaos.ACTION_DELAY:
            chaos.sleep_for(rule)
            return
        raise DriftMonitorError(
            f"chaos-injected monitor failure for job {job_id}")

    def _tick_job(self, job_id: str, predictor) -> Optional[Dict[str, Any]]:
        inf = self._db.get_inference_job(job_id)
        if inf is None or inf["status"] != InferenceJobStatus.RUNNING:
            with self._lock:
                self._jobs.pop(job_id, None)
            return None
        st = self._job_state(job_id)
        phase = st["phase"]
        if phase == DriftPhase.PARKED:
            return None
        if phase == DriftPhase.COOLDOWN:
            if time.time() < float(st.get("cooldown_until") or 0.0):
                return None
            st["phase"] = DriftPhase.WATCHING
            st["baseline"] = None  # refreeze against current traffic
            st["reason"] = None
            self._event(job_id, st, "cooldown_over",
                        detail="cooldown elapsed; watching resumes with "
                               "a fresh baseline")
            self._save(job_id, st)
            return {"job_id": job_id, "action": "watch"}
        if phase == DriftPhase.RETRAINING:
            return self._poll_retrain(job_id, st, inf)
        if phase == DriftPhase.ROLLING_OUT:
            return self._poll_rollout(job_id, st)
        # WATCHING
        if self._rollouts.is_active(job_id):
            return None  # an in-flight rollout owns the serving plane
        min_n = int(config.DRIFT_MIN_SAMPLES)
        if st.get("baseline") is None:
            base = predictor.drift_window(
                float(config.DRIFT_BASELINE_WINDOW_S))
            if len(base) < min_n:
                return None
            st["baseline"] = self._freeze_baseline(base)
            self._event(
                job_id, st, "baseline_frozen",
                detail=f"{st['baseline']['count']} samples, "
                       f"{len(st['baseline']['digests'])} distinct "
                       "digests")
            self._save(job_id, st)
            return {"job_id": job_id, "action": "baseline"}
        samples = predictor.drift_window(float(config.DRIFT_WINDOW_S))
        if len(samples) < min_n:
            return None
        signals = self._signals(st["baseline"], samples)
        st["signals"] = signals  # live snapshot; persisted on verdicts
        reason = self._verdict(signals)
        if reason is None:
            return None
        self._m_events.labels(job_id).inc()
        self._event(job_id, st, "drift", detail=reason, signals=signals)
        budget = int(config.DRIFT_RETRAIN_BUDGET)
        if budget <= 0:
            # monitor-only mode: events fire, the training plane is
            # never touched (doctor WARNs about the 0 budget)
            self._cooldown(
                job_id, st,
                f"monitor-only (retrain budget 0): {reason}")
            return {"job_id": job_id, "action": "drift", "reason": reason}
        st["phase"] = DriftPhase.RETRAINING
        st["reason"] = reason
        st["retrain_job_id"] = None  # write-ahead intent; launch follows
        st["launch_attempts"] = 0
        self._save(job_id, st)
        self._launch_retrain(job_id, st, inf)
        return {"job_id": job_id, "action": "drift", "reason": reason,
                "signals": signals}

    # -- signals ------------------------------------------------------------

    @staticmethod
    def _freeze_baseline(samples: List[tuple]) -> Dict[str, Any]:
        """Sketch the window into the frozen reference population: the
        distinct-digest set (bounded), the mean top probability, and the
        busiest digest's traffic share."""
        digests: List[str] = []
        seen: set = set()
        confs: List[float] = []
        counts: Dict[str, int] = {}
        for _ts, digest, conf in samples:
            if digest is not None:
                counts[digest] = counts.get(digest, 0) + 1
                if digest not in seen and len(seen) < _BASELINE_DIGESTS:
                    seen.add(digest)
                    digests.append(digest)
            if conf is not None:
                confs.append(float(conf))
        total = sum(counts.values())
        return {
            "digests": digests,
            "mean_conf": (sum(confs) / len(confs)) if confs else None,
            "top_share": (max(counts.values()) / total) if total else 0.0,
            "count": len(samples),
            "frozen_at": time.time(),
        }

    @staticmethod
    def _signals(baseline: Dict[str, Any],
                 samples: List[tuple]) -> Dict[str, Any]:
        """The divergence statistics for one window vs the baseline:
        ``novelty`` — fraction of the window's digest draws absent from
        the baseline population (input-distribution shift); ``conf_drop``
        — baseline mean top probability minus the window's (score decay,
        probability tasks only); ``skew`` — growth of the single
        busiest digest's traffic share (one caller dominating the
        door)."""
        base_set = set(baseline.get("digests") or [])
        counts: Dict[str, int] = {}
        confs: List[float] = []
        novel = 0
        total = 0
        for _ts, digest, conf in samples:
            if digest is not None:
                total += 1
                counts[digest] = counts.get(digest, 0) + 1
                if digest not in base_set:
                    novel += 1
            if conf is not None:
                confs.append(float(conf))
        novelty = (novel / total) if total else 0.0
        mean_conf = (sum(confs) / len(confs)) if confs else None
        base_conf = baseline.get("mean_conf")
        conf_drop = ((float(base_conf) - mean_conf)
                     if base_conf is not None and mean_conf is not None
                     else 0.0)
        top_share = (max(counts.values()) / total) if total else 0.0
        skew = top_share - float(baseline.get("top_share") or 0.0)
        return {
            "samples": len(samples),
            "distinct": len(counts),
            "novelty": round(novelty, 4),
            "mean_conf": (round(mean_conf, 4)
                          if mean_conf is not None else None),
            "baseline_conf": (round(float(base_conf), 4)
                              if base_conf is not None else None),
            "conf_drop": round(conf_drop, 4),
            "top_share": round(top_share, 4),
            "skew": round(skew, 4),
        }

    @staticmethod
    def _verdict(signals: Dict[str, Any]) -> Optional[str]:
        if signals["novelty"] >= float(config.DRIFT_THRESHOLD):
            return (f"input distribution shift: novelty "
                    f"{signals['novelty']:.0%} >= "
                    f"{float(config.DRIFT_THRESHOLD):.0%} of the window "
                    "is outside the baseline population")
        if signals["conf_drop"] >= float(config.DRIFT_CONF_DROP):
            return (f"confidence decay: mean top probability fell "
                    f"{signals['conf_drop']:.3f} below the baseline "
                    f"(>= {float(config.DRIFT_CONF_DROP):.3f})")
        if signals["skew"] >= float(config.DRIFT_SKEW_DELTA):
            return (f"traffic skew: the busiest digest's share grew "
                    f"{signals['skew']:.0%} over the baseline "
                    f"(>= {float(config.DRIFT_SKEW_DELTA):.0%})")
        return None

    # -- retrain ------------------------------------------------------------

    def _launch_retrain(self, job_id: str, st: Dict[str, Any],
                        inf: Dict[str, Any]) -> None:
        """One launch attempt per tick (the chaos chokepoint), bounded
        by DRIFT_LAUNCH_RETRY_MAX retries before the loop parks."""
        try:
            self._chaos_launch(job_id)
            retrain = self._create_retrain(inf)
        # lint: absorb(bounded launch retries: each failure is recorded, retried next tick, then parked with a typed event)
        except Exception as e:
            st["launch_attempts"] = int(st.get("launch_attempts") or 0) + 1
            retry_max = int(config.DRIFT_LAUNCH_RETRY_MAX)
            if st["launch_attempts"] > retry_max:
                self._park(
                    job_id, st,
                    f"retrain launch failed {st['launch_attempts']}x "
                    f"(bounded at {retry_max} retries): "
                    f"{type(e).__name__}: {e}")
            else:
                self._event(
                    job_id, st, "retrain_launch_retry",
                    detail=f"attempt {st['launch_attempts']} failed "
                           f"({type(e).__name__}: {e}); retrying next "
                           "tick")
                self._save(job_id, st)
            logger.warning("drift retrain launch failed for job %s",
                           job_id, exc_info=True)
            return
        st["retrain_job_id"] = retrain["id"]
        self._m_retrains.labels(job_id).inc()
        self._event(
            job_id, st, "retrain_launched",
            detail=f"train job {retrain['id'][:8]} (budget "
                   f"{int(config.DRIFT_RETRAIN_BUDGET)} trials, "
                   "warm-started from the incumbent's history)")
        self._save(job_id, st)

    @staticmethod
    def _chaos_launch(job_id: str) -> None:
        rule = chaos.hit(chaos.SITE_DRIFT, f"launch/{job_id}")
        if rule is None:
            return
        if rule.action == chaos.ACTION_DELAY:
            chaos.sleep_for(rule)
            return
        raise DriftLaunchError(
            f"chaos-injected retrain-launch failure for job {job_id}")

    def _create_retrain(self, inf: Dict[str, Any]) -> Dict[str, Any]:
        """Launch the bounded warm-started retrain: same app/task/data
        and model set as the incumbent's train job, MODEL_TRIAL_COUNT
        capped by the drift budget, advisors seeded from the incumbent's
        scored + infeasible trials before the services start."""
        tj = self._db.get_train_job(inf["train_job_id"])
        if tj is None:
            raise DriftLaunchError(
                f"incumbent train job {inf['train_job_id']} not found")
        names = []
        for sub in self._db.get_sub_train_jobs_of_train_job(tj["id"]):
            model = self._db.get_model(sub["model_id"])
            if model is not None:
                names.append(model["name"])
        budget = dict(tj.get("budget") or {})
        budget[BudgetType.MODEL_TRIAL_COUNT] = int(
            config.DRIFT_RETRAIN_BUDGET)
        return self._admin.create_train_job(
            tj["user_id"], tj["app"], tj["task"],
            tj["train_dataset_uri"], tj["test_dataset_uri"],
            budget=budget, model_names=names or None,
            warm_start_from=tj["id"])

    def _poll_retrain(self, job_id: str, st: Dict[str, Any],
                      inf: Dict[str, Any]) -> Optional[Dict[str, Any]]:
        from rafiki_tpu.admin.rollout import RolloutInFlightError

        rid = st.get("retrain_job_id")
        if not rid:
            # a previous launch attempt failed; retry this tick
            self._launch_retrain(job_id, st, inf)
            return None
        tj = self._db.get_train_job(rid)
        if tj is None:
            self._park(job_id, st,
                       f"retrain job {rid[:8]} vanished from the store")
            return {"job_id": job_id, "action": "parked"}
        if tj["status"] == TrainJobStatus.ERRORED:
            self._cooldown(
                job_id, st,
                f"retrain job {rid[:8]} ERRORED"
                + (f": {tj['error_reason']}" if tj.get("error_reason")
                   else ""))
            return {"job_id": job_id, "action": "retrain_errored"}
        if tj["status"] != TrainJobStatus.STOPPED:
            return None  # still training
        best = self._db.get_best_trials_of_train_job(rid, max_count=1)
        cand = best[0] if best else None
        if cand is None or cand.get("score") is None:
            self._cooldown(
                job_id, st,
                f"retrain {rid[:8]} produced no scored candidate")
            return {"job_id": job_id, "action": "no_candidate"}
        incumbent = self._db.get_best_trials_of_train_job(
            inf["train_job_id"], max_count=1)
        inc_score = (incumbent[0]["score"]
                     if incumbent and incumbent[0].get("score") is not None
                     else None)
        if inc_score is not None \
                and float(cand["score"]) <= float(inc_score):
            # a worse candidate costs the serving plane NOTHING: no
            # rollout starts, the loop backs off
            self._cooldown(
                job_id, st,
                f"candidate {cand['id'][:8]} scored "
                f"{float(cand['score']):.4f} <= incumbent "
                f"{float(inc_score):.4f}: keeping the incumbent")
            return {"job_id": job_id, "action": "candidate_worse"}
        try:
            self._rollouts.start(job_id, cand["id"])
        except RolloutInFlightError:
            return None  # a foreign rollout is live; re-check next tick
        # lint: absorb(a refused auto-rollout (validation 400) backs the loop off instead of crashing the tick)
        except Exception as e:
            self._cooldown(job_id, st, f"auto-rollout refused: {e}")
            return {"job_id": job_id, "action": "rollout_refused"}
        st["phase"] = DriftPhase.ROLLING_OUT
        st["candidate_trial_id"] = cand["id"]
        self._event(
            job_id, st, "rollout_started",
            detail=f"candidate {cand['id'][:8]} (score "
                   f"{float(cand['score']):.4f} > incumbent "
                   f"{float(inc_score):.4f})" if inc_score is not None
            else f"candidate {cand['id'][:8]} (score "
                 f"{float(cand['score']):.4f})")
        self._save(job_id, st)
        return {"job_id": job_id, "action": "rollout_started",
                "trial_id": cand["id"]}

    # -- rollout outcome ----------------------------------------------------

    def _poll_rollout(self, job_id: str,
                      st: Dict[str, Any]) -> Optional[Dict[str, Any]]:
        view = self._rollouts.status(job_id)
        cand = st.get("candidate_trial_id")
        if view is None or (cand is not None
                            and view.get("to_trial_id") != cand):
            self._cooldown(job_id, st,
                           "auto-rollout row missing or superseded by an "
                           "operator rollout")
            return {"job_id": job_id, "action": "rollout_lost"}
        phase = view["phase"]
        if phase in RolloutPhase.LIVE:
            return None
        if phase == RolloutPhase.DONE:
            st["consecutive_rollbacks"] = 0
            st["retrain_job_id"] = None
            st["candidate_trial_id"] = None
            st["baseline"] = None  # refreeze against the new model
            st["phase"] = DriftPhase.WATCHING
            st["reason"] = None
            self._m_rollouts.labels(job_id).inc()
            self._event(
                job_id, st, "rollout_done",
                detail=f"candidate {cand[:8] if cand else '?'} is "
                       "serving; the baseline refreezes on its traffic")
            self._save(job_id, st)
            return {"job_id": job_id, "action": "rollout_done"}
        if phase == RolloutPhase.ROLLED_BACK:
            st["consecutive_rollbacks"] = int(
                st.get("consecutive_rollbacks") or 0) + 1
            self._m_rollbacks.labels(job_id).inc()
            acked = ""
            try:
                # the loop acks its own rollback: the drift row carries
                # the flap signal for the doctor, so leaving the rollout
                # row unacked would just add a second, noisier WARN
                if not view.get("operator_ack"):
                    self._rollouts.ack(job_id)
                    acked = "; rollback acked by the drift loop"
            # lint: absorb(the ack is a courtesy: a racing operator ack (or swept row) must not fail the outcome handling)
            except Exception:
                pass
            streak = st["consecutive_rollbacks"]
            self._cooldown(
                job_id, st,
                f"candidate {cand[:8] if cand else '?'} rolled back "
                f"({view.get('reason')}); consecutive rollbacks "
                f"{streak}{acked}",
                backoff=streak)
            return {"job_id": job_id, "action": "rollback"}
        # ABORTED (job stopped mid-rollout, stale row swept, ...)
        self._cooldown(job_id, st,
                       f"auto-rollout aborted ({view.get('reason')})")
        return {"job_id": job_id, "action": "rollout_aborted"}

    # -- transitions --------------------------------------------------------

    def _cooldown(self, job_id: str, st: Dict[str, Any], reason: str,
                  backoff: int = 0) -> None:
        """Enter COOLDOWN for the base cooldown, doubled per consecutive
        rollback (capped at x16) so a flapping candidate backs the loop
        off exponentially instead of storming the training plane."""
        base = float(config.DRIFT_COOLDOWN_S)
        mult = 2 ** min(max(backoff - 1, 0), _BACKOFF_CAP) if backoff \
            else 1
        st["phase"] = DriftPhase.COOLDOWN
        st["cooldown_until"] = time.time() + base * mult
        st["reason"] = reason
        st["retrain_job_id"] = None
        st["candidate_trial_id"] = None
        self._event(job_id, st, "cooldown",
                    detail=f"{reason} (backing off {base * mult:g}s)")
        self._save(job_id, st)

    def _park(self, job_id: str, st: Dict[str, Any], reason: str) -> None:
        st["phase"] = DriftPhase.PARKED
        st["reason"] = reason
        st["operator_ack"] = False
        st["retrain_job_id"] = None
        st["candidate_trial_id"] = None
        self._m_parked.labels(job_id).inc()
        self._event(job_id, st, "parked",
                    detail=f"{reason} — POST .../drift/ack re-arms the "
                           "loop")
        self._save(job_id, st)

    # -- state plumbing -----------------------------------------------------

    def _job_state(self, job_id: str) -> Dict[str, Any]:
        with self._lock:
            st = self._jobs.get(job_id)
        if st is not None:
            return st
        row = self._db.get_drift_state(job_id)
        if row is None:
            row = self._db.create_drift_state(job_id, DriftPhase.WATCHING)
        st = self._state_from_row(row)
        with self._lock:
            # setdefault: a racing tick/ack that loaded first wins
            return self._jobs.setdefault(job_id, st)

    @staticmethod
    def _state_from_row(row: Dict[str, Any]) -> Dict[str, Any]:
        return {
            "phase": row["phase"],
            "reason": row.get("reason"),
            "baseline": row.get("baseline"),
            "signals": row.get("signals"),
            "retrain_job_id": row.get("retrain_job_id"),
            "candidate_trial_id": row.get("candidate_trial_id"),
            "cooldown_until": float(row.get("cooldown_until") or 0.0),
            "consecutive_rollbacks": int(
                row.get("consecutive_rollbacks") or 0),
            "events": list(row.get("events") or []),
            "operator_ack": bool(row.get("operator_ack")),
            "launch_attempts": 0,
        }

    def _save(self, job_id: str, st: Dict[str, Any]) -> None:
        self._db.update_drift_state(
            job_id,
            phase=st["phase"],
            reason=st.get("reason"),
            baseline=st.get("baseline"),
            signals=st.get("signals"),
            retrain_job_id=st.get("retrain_job_id"),
            candidate_trial_id=st.get("candidate_trial_id"),
            cooldown_until=float(st.get("cooldown_until") or 0.0),
            consecutive_rollbacks=int(
                st.get("consecutive_rollbacks") or 0),
            events=st.get("events") or [],
            operator_ack=bool(st.get("operator_ack")),
        )

    def _event(self, job_id: str, st: Dict[str, Any], name: str,
               detail: Optional[str] = None,
               signals: Optional[Dict[str, Any]] = None) -> None:
        evt: Dict[str, Any] = {"ts": time.time(), "job_id": job_id,
                               "event": name, "detail": detail}
        if signals is not None:
            evt["signals"] = signals
        with self._lock:
            self.events.append(evt)
        row_events = list(st.get("events") or [])[-(_ROW_EVENTS - 1):]
        row_events.append({k: v for k, v in evt.items()
                           if k != "job_id"})
        st["events"] = row_events
        logger.info("drift %s for job %s: %s", name, job_id[:8],
                    detail or "")

    # -- operator surface ---------------------------------------------------

    def status(self, inference_job_id: str) -> Optional[Dict[str, Any]]:
        """The job's durable drift row plus the live signal snapshot —
        the GET .../drift view."""
        row = self._db.get_drift_state(inference_job_id)
        if row is None:
            return None
        view = dict(row)
        view["enabled"] = bool(config.DRIFT)
        with self._lock:
            st = self._jobs.get(inference_job_id)
            if st is not None and st.get("signals") is not None:
                view["signals"] = st["signals"]
        return view

    def ack(self, inference_job_id: str) -> Dict[str, Any]:
        """Operator acknowledgment: re-arms a PARKED loop (fresh
        baseline, cleared rollback streak) or clears a standing flap
        counter — both clear the doctor WARNs."""
        from rafiki_tpu.admin.admin import InvalidRequestError

        row = self._db.get_drift_state(inference_job_id)
        if row is None:
            raise InvalidRequestError(
                f"no drift state recorded for job {inference_job_id}")
        with self._lock:
            st = self._jobs.get(inference_job_id)
        if st is None:
            st = self._state_from_row(row)
            with self._lock:
                st = self._jobs.setdefault(inference_job_id, st)
        if st["phase"] == DriftPhase.PARKED:
            st["phase"] = DriftPhase.WATCHING
            st["baseline"] = None
            st["consecutive_rollbacks"] = 0
            st["launch_attempts"] = 0
            st["operator_ack"] = True
            st["reason"] = None
            self._event(inference_job_id, st, "acked",
                        detail="operator ack: loop re-armed")
            self._save(inference_job_id, st)
        elif int(st.get("consecutive_rollbacks") or 0) > 0:
            st["consecutive_rollbacks"] = 0
            st["operator_ack"] = True
            self._event(inference_job_id, st, "acked",
                        detail="operator ack: rollback flap counter "
                               "cleared")
            self._save(inference_job_id, st)
        else:
            raise InvalidRequestError(
                f"nothing to acknowledge for job {inference_job_id} "
                f"(phase {st['phase']}, no rollback streak)")
        return self.status(  # type: ignore[return-value]
            inference_job_id)

    def report(self) -> Dict[str, Any]:
        """The GET /fleet/health "drift" section."""
        with self._lock:
            jobs = {
                job_id: {
                    "phase": st["phase"],
                    "reason": st.get("reason"),
                    "cooldown_until": float(
                        st.get("cooldown_until") or 0.0),
                    "consecutive_rollbacks": int(
                        st.get("consecutive_rollbacks") or 0),
                    "retrain_job_id": st.get("retrain_job_id"),
                    "candidate_trial_id": st.get("candidate_trial_id"),
                    "baseline_frozen": st.get("baseline") is not None,
                    "signals": st.get("signals"),
                }
                for job_id, st in self._jobs.items()
            }
            events = list(self.events)[-20:]
        return {
            "enabled": bool(config.DRIFT),
            "running": self.running,
            "interval_s": float(config.DRIFT_INTERVAL_S),
            "window_s": float(config.DRIFT_WINDOW_S),
            "jobs": jobs,
            "events": events,
        }

    # -- crash recovery (admin/recovery.py) ---------------------------------

    def recover_on_boot(self) -> None:
        """Resume mid-loop state after an admin crash — called by
        ControlPlaneRecovery after the rollout controller's own boot
        pass. RETRAINING with a persisted retrain_job_id just resumes
        polling (the id is the idempotency key: the recovered loop can
        never double-launch). RETRAINING with a NULL id is a write-ahead
        intent whose launch fate is unknowable — the dead admin crashed
        either side of the create — so it is resolved by adopting the
        one train job that matches the intent, else by parking; NEVER by
        relaunching. ROLLING_OUT re-attaches to whatever the rollout
        boot pass decided via the normal outcome poll."""
        for row in self._db.get_drift_states():
            if row["phase"] not in DriftPhase.LIVE:
                continue
            job_id = row["inference_job_id"]
            st = self._state_from_row(row)
            with self._lock:
                st = self._jobs.setdefault(job_id, st)
            if row["phase"] == DriftPhase.RETRAINING \
                    and not row.get("retrain_job_id"):
                adopted = self._adopt_orphan_retrain(job_id, row)
                if adopted:
                    st["retrain_job_id"] = adopted
                    self._event(
                        job_id, st, "retrain_adopted",
                        detail=f"crash mid-launch: adopted train job "
                               f"{adopted[:8]} as the in-flight retrain")
                    self._save(job_id, st)
                else:
                    self._park(
                        job_id, st,
                        "admin crashed mid retrain launch and no "
                        "matching train job was found to adopt — parked "
                        "instead of risking a double launch")
            else:
                self._event(job_id, st, "resumed",
                            detail=f"recovered mid-loop in phase "
                                   f"{row['phase']}")
                self._save(job_id, st)

    def _adopt_orphan_retrain(self, job_id: str,
                              row: Dict[str, Any]) -> Optional[str]:
        """Find the train job a crashed launch may have created: same
        user/app as the incumbent, started no earlier than shortly
        before the intent row was written, and not the incumbent
        itself. Newest wins; None means nothing plausible exists."""
        inf = self._db.get_inference_job(job_id)
        tj = (self._db.get_train_job(inf["train_job_id"])
              if inf else None)
        if tj is None:
            return None
        cutoff = float(row.get("datetime_updated") or 0.0) - 60.0
        for job in self._db.get_train_jobs_of_app(tj["user_id"],
                                                  tj["app"]):
            if job["id"] == tj["id"]:
                continue
            if float(job["datetime_started"]) >= cutoff:
                return job["id"]
        return None
