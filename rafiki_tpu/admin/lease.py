"""Leased control-plane leadership (docs/failure-model.md "Control-plane
HA").

One `LeaseManager` per admin process drives the single `control_lease`
row (db/database.py, migration r20):

- **acquire** — compare-and-set takeover of an absent/expired/own lease;
  every success bumps the monotonic **epoch**. Exactly one admin can hold
  the lease at a time, so exactly one admin is LEADER.
- **renew** — an off-thread loop extends the lease every
  ``RAFIKI_ADMIN_LEASE_RENEW_S`` (default TTL/3). Renewal is CAS'd on
  (holder, epoch): a standby having promoted makes the CAS fail, which
  hard-fences this manager immediately.
- **self-fence** — each successful renewal arms the epoch write-fence on
  every bound :class:`Database` handle with a ``time.monotonic()``
  validity of one TTL. A leader that cannot renew (paused, partitioned,
  store down) simply stops extending the fence, so its own mutating
  writes start raising ``StaleEpochError`` at the moment the TTL lapses —
  BEFORE the standby can acquire, because the standby also waits out the
  TTL on the lease row's wall clock.

Renewal *errors* (chaos ``site=lease``, a flaky store) never drop
leadership by themselves — the false-lease-loss drill: only the TTL clock
or a failed CAS demotes, so a single failed renewal round trip costs
nothing while a genuinely partitioned leader still fences on time.
"""

from __future__ import annotations

import logging
import os
import socket
import threading
import time
import uuid
from typing import Any, Dict, List, Optional

from rafiki_tpu import config
from rafiki_tpu.db.database import Database

logger = logging.getLogger(__name__)

ROLE_LEADER = "leader"
ROLE_FENCED = "fenced"
ROLE_STANDBY = "standby"


class LeaseNotAcquiredError(RuntimeError):
    """Leadership could not be acquired within the boot timeout — another
    admin holds a live lease. Boot the second admin as a hot standby
    (admin/standby.py) instead of a leader."""


def default_holder() -> str:
    """A stable-enough unique holder id: host + pid + random tail (two
    admins on one host — the common test/dev shape — must not collide)."""
    return f"{socket.gethostname()}:{os.getpid()}:{uuid.uuid4().hex[:6]}"


class LeaseManager:
    """Owns one admin's side of the leadership lease: acquisition, the
    off-thread renewal loop, and fence propagation to bound Database
    handles and the placement layer's epoch provider."""

    def __init__(self, db: Database, holder: Optional[str] = None,
                 addr: Optional[str] = None,
                 ttl_s: Optional[float] = None,
                 renew_s: Optional[float] = None):
        self._db = db
        self.holder = holder or default_holder()
        # advertised leader address ("host:port") — rides the lease row so
        # standby 503s and client failover can hint where the leader lives
        self.addr = addr
        self.ttl_s = float(ttl_s if ttl_s is not None
                           else config.ADMIN_LEASE_TTL_S)
        r = renew_s if renew_s is not None else config.ADMIN_LEASE_RENEW_S
        self.renew_s = float(r) if r else self.ttl_s / 3.0
        self._lock = threading.Lock()
        self._epoch: Optional[int] = None  # guarded-by: _lock
        self._valid_until = 0.0  # guarded-by: _lock (monotonic)
        self._suspended = False  # guarded-by: _lock (SIGSTOP drill hook)
        self._dbs: List[Database] = [db]  # guarded-by: _lock
        self._stop_evt = threading.Event()
        self._thread: Optional[threading.Thread] = None

    # -- acquisition -------------------------------------------------------

    def acquire(self, block: bool = False,
                timeout_s: Optional[float] = None) -> bool:
        """Try to take the lease (bumping the epoch). With ``block``,
        keeps retrying every renewal period until ``timeout_s`` — the
        boot path of a leader racing a dying predecessor's TTL."""
        deadline = time.monotonic() + (timeout_s or 0.0)
        while True:
            try:
                row = self._db.acquire_lease(self.holder, self.ttl_s,
                                             addr=self.addr)
            except Exception as e:
                # transient store fault at the chokepoint (chaos
                # site=lease): acquisition just didn't happen this round
                logger.warning("lease acquisition failed: %s", e)
                row = None
            if row is not None:
                valid_until = time.monotonic() + self.ttl_s
                with self._lock:
                    self._epoch = row["epoch"]
                    self._valid_until = valid_until
                    self._arm_fences_locked()
                logger.info("leadership acquired: holder=%s epoch=%d "
                            "ttl=%.1fs", self.holder, row["epoch"],
                            self.ttl_s)
                return True
            if not block or time.monotonic() >= deadline:
                return False
            time.sleep(min(self.renew_s, 0.5))

    # -- fence plumbing ----------------------------------------------------

    def bind(self, db: Database) -> None:
        """Arm the epoch write-fence on another Database handle (the
        promoted Admin's own handle, when it differs from the watcher's)."""
        with self._lock:
            if db not in self._dbs:
                self._dbs.append(db)
            if self._epoch is not None:
                self._arm_fences_locked()

    def _arm_fences_locked(self) -> None:  # guarded-by: _lock
        # caller holds _lock; Database.set_fence takes the handle's own
        # lock — ordering is always LeaseManager._lock -> Database._lock
        for db in self._dbs:
            db.set_fence(self._epoch, self._valid_until)

    # -- renewal loop ------------------------------------------------------

    def start(self) -> "LeaseManager":
        if self._thread is None:
            self._thread = threading.Thread(
                target=self._renew_loop, name="admin-lease-renew",
                daemon=True)
            self._thread.start()
        return self

    def _renew_loop(self) -> None:
        while not self._stop_evt.wait(self.renew_s):
            with self._lock:
                suspended = self._suspended
                epoch = self._epoch
            if suspended or epoch is None:
                continue
            try:
                ok = self._db.renew_lease(self.holder, epoch, self.ttl_s,
                                          addr=self.addr)
            # lint: absorb(false-lease-loss contract: a renewal ERROR must
            # not drop leadership — only the TTL clock or a failed CAS
            # demotes; the miss is logged and the fence simply not
            # extended, so repeated failures self-fence at TTL)
            except Exception as e:
                logger.warning("lease renewal failed (epoch %d), "
                               "self-fence in %.1fs: %s", epoch,
                               self.valid_for_s(), e)
                continue
            if not ok:
                # CAS refused: a newer epoch holds the lease row —
                # leadership is gone for good; hard-fence NOW rather than
                # coasting on the remaining TTL
                logger.error("leadership lost at epoch %d (lease CAS "
                             "refused); fencing all writes", epoch)
                with self._lock:
                    self._valid_until = 0.0
                    self._arm_fences_locked()
                continue
            valid_until = time.monotonic() + self.ttl_s
            with self._lock:
                self._valid_until = valid_until
                self._arm_fences_locked()

    # -- introspection -----------------------------------------------------

    def epoch(self) -> Optional[int]:
        """The epoch this manager holds *validly* — None once the lease
        lapsed (self-fence) or was never acquired."""
        with self._lock:
            if self._epoch is None or time.monotonic() >= self._valid_until:
                return None
            return self._epoch

    def last_epoch(self) -> Optional[int]:
        """The epoch last held, even after self-fencing — what agent
        calls are stamped with, so a stale ex-leader's mutations are
        refused with a *typed* stale-epoch answer instead of an ambiguous
        missing-header one."""
        with self._lock:
            return self._epoch

    def role(self) -> str:
        return ROLE_LEADER if self.epoch() is not None else ROLE_FENCED

    def valid_for_s(self) -> float:
        with self._lock:
            return max(0.0, self._valid_until - time.monotonic())

    def leader_row(self) -> Optional[Dict[str, Any]]:
        """The lease row as stored (doctor / health / leader hints).
        Absorbs store faults — introspection must never crash a door."""
        try:
            return self._db.read_lease()
        except Exception as e:  # lint: absorb(read-only introspection)
            logger.warning("lease read failed: %s", e)
            return None

    def status(self) -> Dict[str, Any]:
        with self._lock:
            epoch = self._epoch
            valid_for = max(0.0, self._valid_until - time.monotonic())
        return {
            "holder": self.holder,
            "addr": self.addr,
            "epoch": epoch,
            "role": (ROLE_LEADER if epoch is not None and valid_for > 0
                     else ROLE_FENCED),
            "ttl_s": self.ttl_s,
            "renew_s": self.renew_s,
            "valid_for_s": round(valid_for, 3),
        }

    # -- drill hooks (SIGSTOP stand-in for in-process tier-1 tests) --------

    def suspend(self) -> None:
        """Freeze renewal — the in-process analogue of SIGSTOP'ing the
        leader: the fence validity lapses on the monotonic clock exactly
        as it would for a stopped process."""
        with self._lock:
            self._suspended = True

    def resume(self) -> None:
        with self._lock:
            self._suspended = False

    # -- shutdown ----------------------------------------------------------

    def stop(self, release: bool = True) -> None:
        """Stop renewing; with ``release`` (graceful shutdown) expire the
        lease now so a standby promotes without waiting out the TTL."""
        self._stop_evt.set()
        if self._thread is not None:
            self._thread.join(timeout=5.0)
        with self._lock:
            epoch = self._epoch
        if release and epoch is not None:
            try:
                self._db.release_lease(self.holder, epoch)
            except Exception as e:  # lint: absorb(best-effort handoff;
                # the TTL expires the lease anyway)
                logger.warning("lease release failed: %s", e)
        with self._lock:
            for db in self._dbs:
                db.clear_fence()
