"""Admin: all orchestration business logic (reference rafiki/admin/admin.py:29-675).

Capability parity: user management with RBAC + seeded superadmin, model CRUD
(template file stored as bytes, validated at upload), train-job lifecycle with
app auto-versioning, trial introspection (status/logs/params), inference-job
lifecycle (requires train job STOPPED, one running inference job per train
job), worker events driving job status.

Architectural difference: Admin composes the in-process stack directly —
store, placement manager, advisor store, broker — instead of shelling out to
Docker through a socket. The HTTP layer (admin/http.py) is a thin shell over
this class, so library use (tests, notebooks, single-host deployments) and
REST use are the same code path.
"""

from __future__ import annotations

import collections
import json
import logging
import os
import threading
import time
from typing import Any, Dict, List, Optional

from rafiki_tpu import config
from rafiki_tpu.advisor.advisor import AdvisorStore
from rafiki_tpu.admin.services import ServicesManager
from rafiki_tpu.cache.shm_broker import make_broker
from rafiki_tpu.constants import (
    InferenceJobStatus,
    ModelAccessRight,
    TrainJobStatus,
    UserType,
)
from rafiki_tpu.db.database import Database, StaleEpochError
from rafiki_tpu.placement.hosts import StaleAdminEpochError
from rafiki_tpu.placement.manager import ChipAllocator, LocalPlacementManager
from rafiki_tpu.sdk.knob import serialize_knob_config
from rafiki_tpu.sdk.log import parse_logs
from rafiki_tpu.sdk.model import (
    InvalidModelClassError,
    load_model_class,
    validate_model_dependencies,
)
from rafiki_tpu.utils.auth import (
    UnauthorizedError,
    generate_token,
    hash_password,
    verify_password,
)
from rafiki_tpu.worker.train import (EVENT_BUDGET_REACHED,
                                     EVENT_TRIAL_FAULT_LIMIT)

logger = logging.getLogger(__name__)


class InvalidRequestError(Exception):
    pass


class Admin:
    def __init__(
        self,
        db: Optional[Database] = None,
        placement: Optional[LocalPlacementManager] = None,
        params_dir: Optional[str] = None,
        recover: bool = True,
        lease=None,
        advertise_addr: Optional[str] = None,
    ):
        """``recover`` (default on) makes boot idempotent on an existing
        store: non-terminal jobs/services left by a crashed admin are
        reconciled against what is actually running — adopt / reschedule /
        fence / error (admin/recovery.py; docs/failure-model.md
        "Control-plane faults"). The snapshot is taken synchronously here
        (state created after this constructor is never touched); the
        reconciliation itself runs off-thread behind a ``recovering ->
        ready`` state the HTTP doors gate on.

        ``lease`` is a LeaseManager that ALREADY holds leadership — the
        hot-standby promotion path (admin/standby.py) passes the one it
        just acquired with. Without it, RAFIKI_ADMIN_HA=1 makes this
        constructor acquire its own lease (blocking up to
        RAFIKI_ADMIN_LEASE_ACQUIRE_TIMEOUT_S) before touching the store;
        HA off (the default) keeps the legacy single-admin behavior with
        zero fencing overhead. ``advertise_addr`` ("host:port") rides the
        lease row as the leader hint standby 503s and client failover
        follow."""
        self.db = db or Database()
        # -- control-plane HA: leadership lease + epoch fence --------------
        # (admin/lease.py; docs/failure-model.md "Control-plane HA").
        # Must be settled BEFORE the first store mutation below
        # (_seed_superadmin / recovery): a leader's writes carry its epoch
        # from the very first one.
        from rafiki_tpu.admin.lease import LeaseManager, LeaseNotAcquiredError

        self._lease: Optional[LeaseManager] = lease
        if self._lease is None and config.ADMIN_HA:
            self._lease = LeaseManager(self.db, addr=advertise_addr)
            if not self._lease.acquire(
                    block=True,
                    timeout_s=config.ADMIN_LEASE_ACQUIRE_TIMEOUT_S):
                raise LeaseNotAcquiredError(
                    "another admin holds a live leadership lease "
                    f"(row: {self._lease.leader_row()}); boot this one as "
                    "a hot standby (admin/standby.py) instead")
        if self._lease is not None:
            # the promoted-standby path hands over a lease bound to the
            # watcher's handle; arm the fence on THIS admin's handle too.
            # The renewal thread starts at the END of this constructor
            # (acquire() armed a full TTL of validity, plenty for boot);
            # starting it here would un-confine every attribute below.
            self._lease.bind(self.db)
        self.advisor_store = AdvisorStore()
        # predict hot path: (user, app, version) -> (ts, Predictor); the
        # epoch counter lets stop-time invalidation win over in-flight
        # resolutions (see predict/_drop_predict_routes)
        self._predict_route_cache: Dict[Any, Any] = {}
        self._predict_route_lock = threading.Lock()
        self._predict_route_epoch = 0
        # serving counters reported by out-of-process inference workers
        # over the event channel (see handle_event / get_inference_job_stats).
        # Bounded LRU: stop-time pruning alone can lose the race with a
        # worker's final drain-window push, so the cap — not the prune — is
        # what makes unbounded growth impossible in a long-lived admin.
        self._remote_serving_stats: "collections.OrderedDict[str, Dict[str, int]]" = (
            collections.OrderedDict())
        self._remote_serving_stats_cap = 512
        # overload control on the admin serving door (/predict/<app>):
        # same bounded in-flight + estimated-wait gate the dedicated
        # predictor port runs (predictor/admission.py); one controller for
        # the whole door — it protects this process, not one job
        from rafiki_tpu.predictor.admission import AdmissionController

        # door="admin": the /predict/<app> route's registry metrics
        # (admitted/shed counters + request-latency histogram) are
        # labeled apart from the per-job dedicated ports
        self._predict_admission = AdmissionController(
            door="admin", shared_tenants=True)
        # RAFIKI_BROKER=shm selects the native cross-process data
        # plane (cache/shm_broker.py); default is in-process.
        # RAFIKI_PLACEMENT=process *requires* it (worker processes attach to
        # the shm segments), so process mode forces the shm broker.
        placement_mode = os.environ.get("RAFIKI_PLACEMENT")
        process_mode = (
            placement is None and placement_mode in ("process", "hosts")
        )
        if process_mode:
            from rafiki_tpu.cache.shm_broker import ShmBroker

            self.broker = ShmBroker()
        else:
            self.broker = make_broker()
        # FleetBroker adds remote (agent-relayed) serving queues on top of
        # whatever local data plane was chosen; pass-through otherwise
        from rafiki_tpu.cache.fleet import FleetBroker

        self.broker = FleetBroker(self.broker)
        if placement is not None:
            self.placement = placement
        elif process_mode:
            from rafiki_tpu.placement.process import ProcessPlacementManager

            local = ProcessPlacementManager(
                db=self.db,
                broker=self.broker,
                on_status=self._on_service_status,
                # admin-embedded engine: TRAIN children outlive an admin
                # crash so boot reconciliation can adopt them by pid
                # (worker/bootstrap.py orphan watchdog; admin/recovery.py).
                # NOT in hosts mode: there this engine is only a fallback,
                # recovery adopts via agents and deliberately never by
                # local pid — a surviving child would just double-run its
                # rescheduled service id.
                orphan_survivable=(placement_mode != "hosts"),
            )
            if placement_mode == "hosts":
                # multi-host: train AND inference go to per-host agents
                # (RAFIKI_AGENTS=host:port,host:port); remote inference
                # workers are reached through the FleetBroker's agent
                # relay, with this host's engine as the serving fallback
                from rafiki_tpu.placement.hosts import HostAgentPlacementManager

                agents = [a.strip() for a in
                          os.environ.get("RAFIKI_AGENTS", "").split(",")
                          if a.strip()]
                self.placement = HostAgentPlacementManager(
                    agents,
                    local=local,
                    key=os.environ.get("RAFIKI_AGENT_KEY"),
                    on_status=self._on_service_status,
                    db=self.db,
                )
            else:
                self.placement = local
        else:
            self.placement = LocalPlacementManager(
                on_status=self._on_service_status
            )
        if self.placement.on_status is None:
            self.placement.on_status = self._on_service_status
        if hasattr(self.placement, "set_broker"):
            # multi-host placement registers remote serving queues with the
            # FleetBroker when it places inference workers on agents
            self.placement.set_broker(self.broker)
        if self._lease is not None and hasattr(self.placement,
                                               "set_epoch_provider"):
            # agent calls carry the leadership epoch (the agent-side half
            # of epoch fencing); last_epoch so a fenced ex-leader still
            # gets the *typed* stale-epoch refusal
            self.placement.set_epoch_provider(self._lease.last_epoch)
        # chip-budget arbitration between the serving and training planes
        # (placement/hosts.py ChipBudgetArbiter): autoscaler scale-ups may
        # borrow idle trial chips; a train executor that can't allocate
        # reclaims them, with RAFIKI_AUTOSCALE_TRAIN_FLOOR chips that the
        # serving plane may never borrow into
        from rafiki_tpu.placement.hosts import ChipBudgetArbiter

        self.chip_arbiter = ChipBudgetArbiter(
            getattr(self.placement, "allocator", None))
        self.services = ServicesManager(
            self.db,
            self.placement,
            self.advisor_store,
            self.broker,
            send_event=self.handle_event,
            params_dir=params_dir,
            arbiter=self.chip_arbiter,
        )
        # the elastic serving control loop (admin/autoscaler.py). The
        # instance always exists — /fleet/health carries its section and
        # the operator scale API goes through the same machinery — but
        # the loop thread only runs when RAFIKI_AUTOSCALE=1.
        from rafiki_tpu.admin.autoscaler import Autoscaler

        self.autoscaler = Autoscaler(self)
        if config.AUTOSCALE:
            self.autoscaler.start()
        # warm standby pool (admin/warm_pool.py): K pre-loaded,
        # pre-warmed standby replicas per hot job, so scale-up and
        # failed-replica replacement become an add_worker route instead
        # of a deploy. Always constructed (fleet health carries its
        # section); the maintenance thread only runs when
        # RAFIKI_AUTOSCALE_WARM_POOL > 0.
        from rafiki_tpu.admin.warm_pool import WarmPool

        self.warm_pool = WarmPool(self)
        if int(config.AUTOSCALE_WARM_POOL) > 0:
            self.warm_pool.start()
        # safe live rollouts (admin/rollout.py): canary -> rolling ->
        # done with automatic rollback, updating a RUNNING inference job
        # to a new trial in place. Constructed before recovery so the
        # boot pass can resolve a crashed admin's half-finished rollout.
        from rafiki_tpu.admin.rollout import RolloutController

        self.rollouts = RolloutController(self)
        # the drift closed loop (admin/drift.py): detection -> bounded
        # warm-started retrain -> SLO-guarded auto-rollout. Always
        # constructed (fleet health + drift status/ack go through it);
        # the monitor thread only runs with RAFIKI_DRIFT=1. Built after
        # the rollout controller (it drives rollouts) and before
        # recovery (whose boot pass resumes mid-loop drift rows).
        from rafiki_tpu.admin.drift import DriftController

        self.drift = DriftController(self)
        if config.DRIFT:
            self.drift.start()
        self._seed_superadmin()
        # -- control-plane crash recovery (admin/recovery.py) -------------
        self._recovery: Dict[str, Any] = {"state": "ready"}
        self._recovery_thread: Optional[threading.Thread] = None
        self._recovery_runner = None
        if recover:
            from rafiki_tpu.admin.recovery import ControlPlaneRecovery

            rec = ControlPlaneRecovery(self)
            # the scan runs HERE, synchronously: the to-reconcile set is
            # frozen before the constructor returns, so jobs created on
            # this fresh admin can never race the reconciler
            snapshot = rec.snapshot()
            if rec.needed(snapshot):
                self._recovery = {"state": "recovering",
                                  "started_at": time.time()}
                self._recovery_runner = rec
                self._recovery_thread = threading.Thread(
                    target=self._run_recovery, args=(rec, snapshot),
                    name="admin-recovery", daemon=True)
                self._recovery_thread.start()
            else:
                self._recovery = rec.empty_report()
        if self._lease is not None:
            # no-op for a promoted standby's already-running lease thread
            self._lease.start()

    def _run_recovery(self, rec, snapshot) -> None:
        try:
            # run() absorbs reconcile failures into the report (state
            # `ready`, failed=True, persisted for doctor) — the doors
            # must open either way
            self._recovery = rec.run(snapshot)
        except Exception:
            # belt for a bug in run() itself: never leave the doors 503ing
            logger.exception("control-plane recovery failed")
            self._recovery = {**rec.report, "state": "ready",
                              "failed": True}

    def recovery_status(self) -> Dict[str, Any]:
        """The boot-reconciliation state/report (``recovering`` while the
        off-thread pass runs; the HTTP doors 503 until ``ready``)."""
        return dict(self._recovery)

    def recovery_public(self) -> Dict[str, Any]:
        """The unauthenticated slice of the recovery state: just enough
        for a credential-less client to wait out a restarting admin. The
        full report (counts, per-service reasons, agent addresses) stays
        behind the admin-rights GET /fleet/health."""
        return {"state": self._recovery.get("state", "ready")}

    # -- control-plane HA (admin/lease.py, admin/standby.py) ---------------

    @property
    def lease(self):
        """This admin's LeaseManager (None when HA is off)."""
        return self._lease

    def ha_role(self) -> str:
        """``leader`` (HA off counts as leader — there is nobody else),
        or ``fenced`` once this admin's lease lapsed or was taken over."""
        if self._lease is None:
            return "leader"
        return self._lease.role()

    def ha_epoch(self) -> Optional[int]:
        return self._lease.last_epoch() if self._lease is not None else None

    def leader_hint(self) -> Optional[str]:
        """The current lease holder's advertised address — what standby /
        fenced 503s carry so clients fail over straight to the leader."""
        if self._lease is None:
            return None
        row = self._lease.leader_row()
        return row.get("addr") if row else None

    def ha_public(self) -> Dict[str, Any]:
        """Unauthenticated HA slice for the public root: role + leader
        hint (no holder ids, no lease internals)."""
        if self._lease is None:
            return {"role": "leader"}
        return {"role": self._lease.role(), "leader": self.leader_hint()}

    # -- users ---------------------------------------------------------------

    def _seed_superadmin(self) -> None:
        if self.db.get_user_by_email(config.SUPERADMIN_EMAIL) is None:
            self.db.create_user(
                config.SUPERADMIN_EMAIL,
                hash_password(config.SUPERADMIN_PASSWORD),
                UserType.SUPERADMIN,
            )

    def authenticate_user(self, email: str, password: str) -> Dict[str, Any]:
        user = self.db.get_user_by_email(email)
        if user is None or not verify_password(password, user["password_hash"]):
            raise UnauthorizedError("Invalid email or password")
        if user["banned"]:
            raise UnauthorizedError("User is banned")
        token = generate_token(
            {"user_id": user["id"], "user_type": user["user_type"]}
        )
        return {
            "user_id": user["id"],
            "user_type": user["user_type"],
            "token": token,
        }

    def create_user(self, email: str, password: str, user_type: str) -> Dict:
        if self.db.get_user_by_email(email) is not None:
            raise InvalidRequestError(f"User {email} already exists")
        user = self.db.create_user(email, hash_password(password), user_type)
        return self._user_view(user)

    def get_users(self) -> List[Dict]:
        return [self._user_view(u) for u in self.db.get_users()]

    def ban_user(self, email: str) -> Dict:
        user = self.db.get_user_by_email(email)
        if user is None:
            raise InvalidRequestError(f"No such user {email}")
        self.db.ban_user(user["id"])
        return self._user_view({**user, "banned": 1})

    @staticmethod
    def _user_view(user: Dict) -> Dict:
        return {
            "id": user["id"],
            "email": user["email"],
            "user_type": user["user_type"],
            "banned": bool(user["banned"]),
        }

    # -- models ----------------------------------------------------------------

    def create_model(
        self,
        user_id: str,
        name: str,
        task: str,
        model_file_bytes: bytes,
        model_class: str,
        dependencies: Optional[Dict[str, Optional[str]]] = None,
        access_right: str = ModelAccessRight.PRIVATE,
    ) -> Dict:
        # validate at upload, not at trial time: class loads, subclasses
        # BaseModel, declares a sane knob config, deps importable. With
        # RAFIKI_INSTALL_DEPS=1 missing deps are accepted here — workers
        # provision them per dependency-set at first use (sdk/deps.py,
        # the reference's install synthesis re-homed,
        # reference model/model.py:244-273)
        from rafiki_tpu.sdk.deps import install_enabled

        # static verification FIRST (analysis/template.py): AST passes
        # over the uploaded source — the platform catches a bad template
        # HERE, not after it has burned trial budget and chip-hours, and
        # at enforce a hostile template (sandbox-forbidden imports) is
        # rejected BEFORE load_model_class executes its module top level
        # in this process. enforce rejects on error findings (typed
        # ModelVerificationError -> 400 at the door); warn persists
        # findings on the row and logs; off skips (doctor WARNs while
        # jobs are live). With dependencies=None the verifier reads the
        # class's literal ``dependencies`` attribute statically.
        report = self._verify_template(
            model_file_bytes, model_class, dependencies, enforce=True)
        clazz = load_model_class(model_file_bytes, model_class)
        # task/capability consistency (docs/serving-generation.md): a
        # generative template under a classification task — or a
        # classification template under TEXT_GENERATION — is a typed 400
        # HERE, not a trial-time crash or a deploy-time surprise
        self._validate_task_capability(task, clazz, report)
        missing = validate_model_dependencies(clazz)
        if missing and not install_enabled():
            raise InvalidModelClassError(
                f"Dependencies not available in this environment: {missing} "
                f"(set RAFIKI_INSTALL_DEPS=1 to let workers provision them)"
            )
        serialize_knob_config(clazz.get_knob_config())
        effective_deps = dependencies or dict(
            getattr(clazz, "dependencies", {}) or {})
        if self.db.get_model_by_name(user_id, name) is not None:
            raise InvalidRequestError(f"Model {name} already exists for user")
        model = self.db.create_model(
            user_id,
            name,
            task,
            model_file_bytes,
            model_class,
            effective_deps,
            access_right,
            verification=json.dumps(report.to_dict()) if report else None,
        )
        return self._model_view(model)

    @staticmethod
    def _model_generation_capable(model_row: Dict) -> bool:
        """Generation capability of a STORED model row: the persisted
        verification report when one exists, else a fresh static pass
        over the stored bytes (never executes the template)."""
        verification = model_row.get("verification")
        if isinstance(verification, str):
            try:
                verification = json.loads(verification)
            except ValueError:
                verification = None
        caps = (verification or {}).get("capabilities") or {}
        if "generation" in caps:
            return bool(caps.get("generation"))
        from rafiki_tpu import analysis

        return analysis.static_generation_capability(
            model_row["model_file_bytes"],
            model_row.get("model_class")) is not None

    @staticmethod
    def _validate_task_capability(task: str, clazz: type, report) -> None:
        """Task-type plumbing for the generative subsystem: the uploaded
        template's statically-derived capability (or the runtime oracle
        when verification ran =off) must MATCH the declared task. Both
        mismatch directions raise the typed InvalidModelClassError the
        HTTP door already maps to 400."""
        from rafiki_tpu.constants import TaskType
        from rafiki_tpu.sdk.model import generation_capability

        if report is not None and "generation" in (
                getattr(report, "capabilities", None) or {}):
            capable = bool(report.capabilities.get("generation"))
        else:
            capable = generation_capability(clazz) is not None
        if task == TaskType.TEXT_GENERATION and not capable:
            raise InvalidModelClassError(
                f"task {task} requires a generation-capable template: "
                "declare a GenerationSpec class attribute and override "
                "init_kv_cache/prefill/decode_step (sdk/model.py; a "
                "half-wired spec does not count — see the GEN001 finding)")
        if capable and task != TaskType.TEXT_GENERATION:
            raise InvalidModelClassError(
                f"template advertises a GenerationSpec but was uploaded "
                f"under task {task}: generative templates must be "
                f"uploaded under task {TaskType.TEXT_GENERATION} (their "
                "serving path is the token-streaming decode loop, which "
                f"a {task} inference job would never deploy)")

    @staticmethod
    def _verify_template(model_file_bytes: bytes, model_class: str,
                         dependencies: Optional[Dict[str, Optional[str]]],
                         enforce: bool):
        """Run the template verifier under the RAFIKI_VERIFY_TEMPLATES
        mode; returns the report (None when mode=off). ``enforce=False``
        is the dry-run path (verify_model) — report only, never raise."""
        from rafiki_tpu import analysis

        mode = analysis.verify_mode()
        if mode == "off":
            return None
        report = analysis.verify_template_bytes(
            model_file_bytes, model_class, dependencies)
        if report.findings:
            logger.warning(
                "template %s static verification: %s", model_class,
                "; ".join(str(f) for f in report.findings[:10]))
        if enforce and mode == "enforce" and not report.ok:
            raise analysis.ModelVerificationError(report)
        return report

    def verify_model(
        self,
        model_file_bytes: bytes,
        model_class: str,
        dependencies: Optional[Dict[str, Optional[str]]] = None,
    ) -> Dict:
        """Dry-run the template verifier (POST /models/verify): the full
        report as JSON, no model row created, nothing rejected — the
        pre-upload loop clients iterate against. Runs even when
        RAFIKI_VERIFY_TEMPLATES=off (an explicit dry-run request is an
        explicit request)."""
        from rafiki_tpu import analysis

        report = analysis.verify_template_bytes(
            model_file_bytes, model_class, dependencies)
        return {"mode": analysis.verify_mode(), **report.to_dict()}

    def get_models(
        self, user_id: str, task: Optional[str] = None
    ) -> List[Dict]:
        """Models visible to `user_id`: their own + PUBLIC ones."""
        return [
            self._model_view(m)
            for m in self.db.get_models(task)
            if m["user_id"] == user_id
            or m["access_right"] == ModelAccessRight.PUBLIC
        ]

    def _resolve_model(
        self, user_id: str, name: str, owner_id: Optional[str]
    ) -> Dict:
        """Resolve a model by name: explicit owner if given, else the
        caller's own, else any PUBLIC model of that name (so listed public
        models are actually fetchable)."""
        model = self.db.get_model_by_name(owner_id or user_id, name)
        if model is None and owner_id is None:
            model = next(
                (
                    m
                    for m in self.db.get_models()
                    if m["name"] == name
                    and m["access_right"] == ModelAccessRight.PUBLIC
                ),
                None,
            )
        if model is None:
            raise InvalidRequestError(f"No such model {name}")
        self._check_model_access(model, user_id)
        return model

    def get_model(self, user_id: str, name: str, owner_id: Optional[str] = None) -> Dict:
        return self._model_view(self._resolve_model(user_id, name, owner_id))

    def get_model_file(
        self, user_id: str, name: str, owner_id: Optional[str] = None
    ) -> bytes:
        return self._resolve_model(user_id, name, owner_id)["model_file_bytes"]

    def delete_model(self, user_id: str, name: str) -> None:
        model = self.db.get_model_by_name(user_id, name)
        if model is None:
            raise InvalidRequestError(f"No such model {name}")
        self.db.delete_model(model["id"])

    @staticmethod
    def _check_model_access(model: Dict, user_id: str) -> None:
        if (
            model["user_id"] != user_id
            and model["access_right"] != ModelAccessRight.PUBLIC
        ):
            raise UnauthorizedError("Model is private")

    @staticmethod
    def _model_view(model: Dict) -> Dict:
        # verification rides the row as a JSON blob (db migration r9);
        # rows from before the verifier (or uploaded under =off) carry
        # None — doctor's "static analysis" check lists those
        verification = model.get("verification")
        if isinstance(verification, str):
            try:
                verification = json.loads(verification)
            except ValueError:
                verification = None
        return {
            "id": model["id"],
            "user_id": model["user_id"],
            "name": model["name"],
            "task": model["task"],
            "model_class": model["model_class"],
            "dependencies": model["dependencies"],
            "access_right": model["access_right"],
            "verification": verification,
        }

    # -- train jobs -------------------------------------------------------------

    def create_train_job(
        self,
        user_id: str,
        app: str,
        task: str,
        train_dataset_uri: str,
        test_dataset_uri: str,
        budget: Optional[Dict[str, Any]] = None,
        model_names: Optional[List[str]] = None,
        warm_start_from: Optional[str] = None,
    ) -> Dict:
        """``warm_start_from`` (a prior train job id) seeds each new
        sub-job's advisor with the source job's scored + infeasible
        trials for models the two jobs share — the drift loop's cheap
        warm-started retrain (admin/drift.py). Seeding happens BEFORE
        the train services launch, so the first proposal already
        benefits; the TrainWorker's own create_advisor/replay are
        idempotent no-ops against the seeded session."""
        budget = {} if budget is None else budget
        self._validate_budget(budget)
        # pick the models: named ones, or all visible models for the task
        # (reference admin.py:118-161)
        # public models first, then the caller's own — so a same-named PUBLIC
        # model from another user can never shadow the caller's own model
        all_models = self.db.get_models(task)
        visible = {
            m["name"]: m
            for m in all_models
            if m["access_right"] == ModelAccessRight.PUBLIC
            and m["user_id"] != user_id
        }
        visible.update(
            {m["name"]: m for m in all_models if m["user_id"] == user_id}
        )
        if model_names is not None:
            missing = [n for n in model_names if n not in visible]
            if missing:
                raise InvalidRequestError(
                    f"Models not found (or private): {missing}"
                )
            models = [visible[n] for n in model_names]
        else:
            models = list(visible.values())
        if not models:
            raise InvalidRequestError(f"No usable models for task {task}")
        # generative task plumbing: every chosen template must actually be
        # able to serve the task — rows uploaded before the capability
        # check existed (or under RAFIKI_VERIFY_TEMPLATES=off) are
        # re-checked statically (zero uploaded code executes), so the
        # mismatch is a typed 400 here instead of a trial-time crash
        from rafiki_tpu.constants import TaskType

        if task == TaskType.TEXT_GENERATION:
            incapable = [m["name"] for m in models
                         if not self._model_generation_capable(m)]
            if incapable:
                raise InvalidRequestError(
                    f"task {task} needs generation-capable templates, but "
                    f"{incapable} advertise no fully-wired GenerationSpec "
                    "(init_kv_cache/prefill/decode_step; sdk/model.py)")

        version = self.db.get_next_app_version(user_id, app)
        job = self.db.create_train_job(
            user_id,
            app,
            version,
            task,
            train_dataset_uri,
            test_dataset_uri,
            budget,
        )
        for m in models:
            self.db.create_sub_train_job(job["id"], m["id"])
        if warm_start_from:
            self._seed_advisors_from(job["id"], warm_start_from)
        self.services.create_train_services(job["id"])
        return self.get_train_job(user_id, app, version)

    def _seed_advisors_from(self, train_job_id: str,
                            source_job_id: str) -> None:
        """Warm-start the new job's advisors from a prior job's trial
        history (matched per model id): replay scored feedback AND
        infeasible observations, mirroring recovery's advisor rebuild.
        Best-effort — a failed seed degrades to a cold-started search,
        never a failed job creation."""
        from rafiki_tpu.constants import TrialStatus
        from rafiki_tpu.sdk.model import load_model_class
        from rafiki_tpu.worker.faults import is_infeasible_row

        source_subs = {
            s["model_id"]: s
            for s in self.db.get_sub_train_jobs_of_train_job(source_job_id)}
        for sub in self.db.get_sub_train_jobs_of_train_job(train_job_id):
            src = source_subs.get(sub["model_id"])
            if src is None:
                continue
            try:
                trials = self.db.get_trials_of_sub_train_job(src["id"])
                scored = [
                    (t["knobs"], t["score"]) for t in trials
                    if t["status"] == TrialStatus.COMPLETED
                    and t["score"] is not None]
                infeasible = [
                    (t["knobs"], t["fault_kind"]) for t in trials
                    if is_infeasible_row(t)]
                if not (scored or infeasible):
                    continue
                model = self.db.get_model(sub["model_id"])
                clazz = load_model_class(model["model_file_bytes"],
                                         model["model_class"])
                self.advisor_store.create_advisor(
                    clazz.get_knob_config(), advisor_id=sub["id"])
                if self.advisor_store.replay_feedback(
                        sub["id"], scored, infeasible=infeasible):
                    logger.info(
                        "advisor %s warm-started with %d scored + %d "
                        "infeasible trials from job %s", sub["id"][:8],
                        len(scored), len(infeasible), source_job_id[:8])
            # lint: absorb(warm start is best-effort: a failed seed cold-starts the search instead of failing job creation)
            except Exception:
                logger.exception("advisor warm start failed for sub %s",
                                 sub["id"][:8])

    @staticmethod
    def _validate_budget(budget: Dict[str, Any]) -> None:
        """Reject malformed budgets at job creation — a bad value silently
        degrading the job later (e.g. ASHA_ETA=1 disabling early stopping
        with a warning per epoch) is strictly worse than a 400 here."""
        from rafiki_tpu.constants import BudgetType

        if not isinstance(budget, dict):
            raise InvalidRequestError(
                f"budget must be a JSON object, got {type(budget).__name__}")

        def as_int(key, minimum):
            raw = budget.get(key)
            if raw is None:
                return
            try:
                v = int(raw)
            except (TypeError, ValueError):
                raise InvalidRequestError(f"budget {key}={raw!r} is not an "
                                          "integer")
            if v < minimum:
                raise InvalidRequestError(
                    f"budget {key}={v} must be >= {minimum}")

        def as_float(key, minimum, exclusive=False):
            raw = budget.get(key)
            if raw is None:
                return
            try:
                v = float(raw)
            except (TypeError, ValueError):
                raise InvalidRequestError(
                    f"budget {key}={raw!r} is not a number")
            import math

            # NaN would pass every comparison and silently disable the
            # limit the value exists to enforce
            if not math.isfinite(v):
                raise InvalidRequestError(f"budget {key}={v} is not finite")
            if v < minimum or (exclusive and v == minimum):
                op = ">" if exclusive else ">="
                raise InvalidRequestError(
                    f"budget {key}={v} must be {op} {minimum}")

        as_int(BudgetType.MODEL_TRIAL_COUNT, 1)
        as_int(BudgetType.CHIP_COUNT, 0)
        as_int(BudgetType.GPU_COUNT, 0)
        as_int(BudgetType.CHIPS_PER_TRIAL, 1)
        as_int(BudgetType.ASHA_MIN_EPOCHS, 1)
        as_int(BudgetType.ASHA_ETA, 2)
        # TIME_HOURS=0 is legal: the deadline is already spent, so the job
        # stops before running any trial (tested behavior)
        as_float(BudgetType.TIME_HOURS, 0)
        as_float(BudgetType.TRIAL_TIMEOUT_S, 0, exclusive=True)
        as_int(BudgetType.CHIPS_PER_WORKER, 1)
        as_int(BudgetType.ENSEMBLE_FUSED, 0)

    def get_train_job(
        self, user_id: str, app: str, app_version: int = -1
    ) -> Dict:
        job = self.db.get_train_job_by_app_version(user_id, app, app_version)
        if job is None:
            raise InvalidRequestError(f"No such train job {app} v{app_version}")
        workers = self.db.get_workers_of_train_job(job["id"])
        services = [self.db.get_service(w["service_id"]) for w in workers]
        return {
            "id": job["id"],
            "app": job["app"],
            "app_version": job["app_version"],
            "task": job["task"],
            "status": job["status"],
            # trial fault taxonomy: why an ERRORED job errored (e.g.
            # fail-fast on a broken template) — None for healthy jobs
            "fault_kind": job.get("fault_kind"),
            "error_reason": job.get("error_reason"),
            "budget": job["budget"],
            "train_dataset_uri": job["train_dataset_uri"],
            "test_dataset_uri": job["test_dataset_uri"],
            "datetime_started": job["datetime_started"],
            "datetime_stopped": job["datetime_stopped"],
            "workers": [
                {
                    "service_id": s["id"],
                    "status": s["status"],
                    "chips": s["chips"],
                }
                for s in services
                if s
            ],
        }

    def get_train_jobs_of_user(self, user_id: str) -> List[Dict]:
        """Light listing for dashboards: one row per train job, no worker
        fan-out (the web UI's landing view)."""
        return [
            {
                "id": j["id"],
                "app": j["app"],
                "app_version": j["app_version"],
                "task": j["task"],
                "status": j["status"],
                "budget": j["budget"],
                "datetime_started": j["datetime_started"],
                "datetime_stopped": j["datetime_stopped"],
            }
            for j in self.db.get_train_jobs_of_user(user_id)
        ]

    def get_train_jobs_of_app(self, user_id: str, app: str) -> List[Dict]:
        return [
            self.get_train_job(user_id, app, j["app_version"])
            for j in self.db.get_train_jobs_of_app(user_id, app)
        ]

    def stop_train_job(self, user_id: str, app: str, app_version: int = -1) -> Dict:
        job = self.db.get_train_job_by_app_version(user_id, app, app_version)
        if job is None:
            raise InvalidRequestError(f"No such train job {app} v{app_version}")
        self.services.stop_train_services(job["id"])
        self.db.mark_train_job_as_stopped(job["id"])
        return self.get_train_job(user_id, app, job["app_version"])

    def wait_until_train_job_stopped(
        self, user_id: str, app: str, app_version: int = -1, timeout_s: float = 600
    ) -> Dict:
        """Convenience for tests/CLI: poll until the job leaves RUNNING."""
        import time as _time

        deadline = _time.time() + timeout_s
        while True:
            job = self.get_train_job(user_id, app, app_version)
            if job["status"] in (TrainJobStatus.STOPPED, TrainJobStatus.ERRORED):
                return job
            if _time.time() > deadline:
                raise TimeoutError(f"Train job still {job['status']}")
            _time.sleep(0.1)

    # -- trials -----------------------------------------------------------------

    def get_trials_of_train_job(
        self, user_id: str, app: str, app_version: int = -1
    ) -> List[Dict]:
        job = self.db.get_train_job_by_app_version(user_id, app, app_version)
        if job is None:
            raise InvalidRequestError(f"No such train job {app} v{app_version}")
        return [self._trial_view(t) for t in self.db.get_trials_of_train_job(job["id"])]

    def get_best_trials_of_train_job(
        self, user_id: str, app: str, app_version: int = -1, max_count: int = 2
    ) -> List[Dict]:
        job = self.db.get_train_job_by_app_version(user_id, app, app_version)
        if job is None:
            raise InvalidRequestError(f"No such train job {app} v{app_version}")
        return [
            self._trial_view(t)
            for t in self.db.get_best_trials_of_train_job(job["id"], max_count)
        ]

    def get_trial(self, trial_id: str) -> Dict:
        trial = self.db.get_trial(trial_id)
        if trial is None:
            raise InvalidRequestError(f"No such trial {trial_id}")
        return self._trial_view(trial)

    def get_trial_logs(self, trial_id: str) -> Dict:
        if self.db.get_trial(trial_id) is None:
            raise InvalidRequestError(f"No such trial {trial_id}")
        return parse_logs(self.db.get_trial_logs(trial_id))

    def get_trial_trace(self, trial_id: str) -> List[Dict]:
        """Per-phase span breakdown recorded by the train worker (the
        tracing subsystem the reference lacks, SURVEY.md §5.1)."""
        if self.db.get_trial(trial_id) is None:
            raise InvalidRequestError(f"No such trial {trial_id}")
        from rafiki_tpu.utils.trace import load_trace

        return load_trace(trial_id)

    def get_trial_params(self, trial_id: str) -> bytes:
        trial = self.db.get_trial(trial_id)
        if trial is None or not trial.get("params_file_path"):
            raise InvalidRequestError(f"No params for trial {trial_id}")
        from rafiki_tpu.sdk.artifact import read_artifact

        # verified read: a damaged params file surfaces as the typed
        # ArtifactCorruptError (a clean error at the door) — the raw
        # payload handed to clients stays plain msgpack either way
        return read_artifact(trial["params_file_path"])

    @staticmethod
    def _trial_view(trial: Dict) -> Dict:
        return {
            "id": trial["id"],
            "sub_train_job_id": trial["sub_train_job_id"],
            "model_id": trial["model_id"],
            "knobs": trial["knobs"],
            "score": trial["score"],
            "status": trial["status"],
            # fault taxonomy (worker/faults.py): how many infra-class
            # re-runs the trial absorbed, plus the typed kind +
            # truncated traceback of its LAST fault (terminal for
            # ERRORED trials; the absorbed transient for COMPLETED ones
            # with attempt > 0) — diagnosing a failure never requires
            # scraping worker logs
            "attempt": trial.get("attempt", 0),
            "fault_kind": trial.get("fault_kind"),
            "fault_detail": trial.get("fault_detail"),
            "datetime_started": trial["datetime_started"],
            "datetime_stopped": trial["datetime_stopped"],
        }

    # -- inference jobs ----------------------------------------------------------

    def create_inference_job(
        self, user_id: str, app: str, app_version: int = -1,
        budget: Optional[Dict[str, Any]] = None,
    ) -> Dict:
        """``budget`` (serving-side, optional): ``CHIPS_PER_WORKER`` >= 1
        grants every inference worker a multi-chip mesh, so one model
        serves its pjit'd predict sharded across chips (the serving
        analogue of CHIPS_PER_TRIAL; the reference was hard-wired to one
        GPU per serving worker, reference services_manager.py:390-395).
        ``ENSEMBLE_FUSED`` truthy co-locates ALL best trials in each
        worker: one vmapped device dispatch serves the whole ensemble when
        the trials share a compiled predict (admin/services.py)."""
        # malformed input 400s regardless of job state (route-boundary
        # validation, same policy as create_train_job)
        self._validate_budget(budget or {})
        job = self.db.get_train_job_by_app_version(user_id, app, app_version)
        if job is None:
            raise InvalidRequestError(f"No such train job {app} v{app_version}")
        if job["status"] != TrainJobStatus.STOPPED:
            # train must have fully stopped first (reference admin.py:360-361)
            raise InvalidRequestError(
                f"Train job must be STOPPED, is {job['status']}"
            )
        if self.db.get_running_inference_job_of_train_job(job["id"]) is not None:
            # one running inference job per train job (reference :363-366)
            raise InvalidRequestError(
                "An inference job is already running for this train job"
            )
        inf = self.db.create_inference_job(user_id, job["id"], budget=budget)
        self.services.create_inference_services(inf["id"])
        return self.get_inference_job(user_id, app, job["app_version"])

    def get_inference_job_stats(
        self, user_id: str, app: str, app_version: int = -1
    ) -> Dict:
        """Serving observability: per-worker batch/query counters and the
        derived batch occupancy (mean queries/batch — the signal that
        continuous batching coalesces under load). In-process workers are
        read from worker/inference.py SERVING_STATS directly; process-mode
        workers relay theirs over the event channel (every ~5 s while
        counters change, so freshly-started remote workers may briefly
        read 0). Counters reset with the worker."""
        from rafiki_tpu.worker.inference import serving_stats

        inf = self.get_inference_job(user_id, app, app_version)
        local = serving_stats()
        workers = []
        total_b = total_q = 0
        for w in inf["workers"]:
            # in-process workers land in the local module counters;
            # process-mode workers report over the event channel
            with self._predict_route_lock:
                remote = self._remote_serving_stats.get(w["service_id"])
            s = local.get(w["service_id"]) or remote or {
                "batches": 0, "queries": 0}
            total_b += s["batches"]
            total_q += s["queries"]
            workers.append({**w, **s})
        return {
            "inference_job_id": inf["id"],
            "status": inf["status"],
            "workers": workers,
            "batches": total_b,
            "queries": total_q,
            "batch_occupancy": round(total_q / total_b, 2) if total_b else None,
        }

    def get_inference_job(
        self, user_id: str, app: str, app_version: int = -1
    ) -> Dict:
        job = self.db.get_train_job_by_app_version(user_id, app, app_version)
        if job is None:
            raise InvalidRequestError(f"No such train job {app} v{app_version}")
        infs = self.db.get_inference_jobs_of_train_job(job["id"])
        if not infs:
            raise InvalidRequestError("No inference job for this train job")
        inf = infs[0]
        workers = self.db.get_workers_of_inference_job(inf["id"])
        # dedicated serving endpoint, when config.PREDICTOR_PORTS bound one
        # (reference parity: the job info carried the predictor's published
        # host port, reference admin/services_manager.py:379-384)
        predictor_host = predictor_port = None
        if inf.get("predictor_service_id"):
            psvc = self.db.get_service(inf["predictor_service_id"])
            if psvc:
                predictor_host = psvc.get("host")
                predictor_port = psvc.get("port")
        def _chips(service_id: str) -> list:
            svc = self.db.get_service(service_id)
            return (svc or {}).get("chips") or []

        return {
            "id": inf["id"],
            "train_job_id": job["id"],
            "app": app,
            "app_version": job["app_version"],
            "predictor_host": predictor_host,
            "predictor_port": predictor_port,
            "status": inf["status"],
            "budget": inf.get("budget") or {},
            "datetime_started": inf["datetime_started"],
            "datetime_stopped": inf["datetime_stopped"],
            "workers": [
                {"service_id": w["service_id"], "trial_id": w["trial_id"],
                 "chips": _chips(w["service_id"])}
                for w in workers
            ],
        }

    def scale_inference_job(
        self, user_id: str, app: str, app_version: int = -1,
        delta: int = 1,
    ) -> Dict:
        """Operator-facing elastic scaling: add (``delta`` > 0) or
        gracefully drain (``delta`` < 0) serving replicas of the app's
        RUNNING inference job without a redeploy — the same primitive the
        autoscaler drives (admin/services.py scale_inference_job)."""
        if not delta:
            raise InvalidRequestError("delta must be a non-zero integer")
        # sanity bound: each added replica is a synchronous placement +
        # deploy wait on this HTTP worker — an unbounded delta would tie
        # the door up for hours mass-creating services
        limit = max(int(config.AUTOSCALE_MAX_REPLICAS), 8)
        if abs(int(delta)) > limit:
            raise InvalidRequestError(
                f"delta {delta} out of range (|delta| <= {limit}; raise "
                "RAFIKI_AUTOSCALE_MAX_REPLICAS to scale further)")
        job = self.db.get_train_job_by_app_version(user_id, app, app_version)
        if job is None:
            raise InvalidRequestError(f"No such train job {app} v{app_version}")
        inf = self.db.get_running_inference_job_of_train_job(job["id"])
        if inf is None:
            raise InvalidRequestError("No running inference job")
        from rafiki_tpu.admin.services import ServiceDeploymentError

        try:
            report = self.services.scale_inference_job(inf["id"], int(delta))
        except ServiceDeploymentError as e:
            raise InvalidRequestError(str(e))
        return {
            "inference_job_id": inf["id"],
            **report,
            "replicas": len(self.services.live_inference_workers(inf["id"])),
        }

    def stop_inference_job(
        self, user_id: str, app: str, app_version: int = -1
    ) -> Dict:
        job = self.db.get_train_job_by_app_version(user_id, app, app_version)
        if job is None:
            raise InvalidRequestError(f"No such train job {app} v{app_version}")
        inf = self.db.get_running_inference_job_of_train_job(job["id"])
        if inf is None:
            raise InvalidRequestError("No running inference job")
        # a rollout mid-flight must end (ABORTED, no rollback pass — the
        # stop below tears the whole fleet down) before the teardown, or
        # its thread would race the stop placing replicas
        self.rollouts.abort_for_job(inf["id"], "inference job stopped")
        self.services.stop_inference_services(inf["id"])
        self._drop_predict_routes(inf["id"])
        return self.get_inference_job(user_id, app, job["app_version"])

    # -- safe live rollouts (admin/rollout.py; docs/failure-model.md
    # "Rollout faults") ------------------------------------------------------

    def _running_inference_job(self, user_id: str, app: str,
                               app_version: int) -> Dict:
        # version -1 means "the serving version", NOT "the newest train
        # job": a drift auto-retrain (admin/drift.py) bumps the app's
        # version catalog without deploying, so the newest version may
        # have no inference job while an older one is still serving
        if app_version == -1:
            for job in self.db.get_train_jobs_of_app(user_id, app):
                inf = self.db.get_running_inference_job_of_train_job(
                    job["id"])
                if inf is not None:
                    return inf
            raise InvalidRequestError("No running inference job")
        job = self.db.get_train_job_by_app_version(user_id, app, app_version)
        if job is None:
            raise InvalidRequestError(f"No such train job {app} v{app_version}")
        inf = self.db.get_running_inference_job_of_train_job(job["id"])
        if inf is None:
            raise InvalidRequestError("No running inference job")
        return inf

    def update_inference_job(
        self, user_id: str, app: str, app_version: int = -1,
        trial_id: Optional[str] = None,
        canary_fraction: Optional[float] = None,
        batch: Optional[int] = None,
    ) -> Dict:
        """Update the app's RUNNING inference job to serve ``trial_id``
        in place — canary, SLO-judged, rolling replace, automatic
        rollback — without a redeploy outage. Answers immediately with
        the rollout row (phase CANARY); poll the status route (or
        ``Client.wait_until_rollout_done``) for the verdict. A second
        update while one is in flight raises the typed
        RolloutInFlightError (→ 409)."""
        if not trial_id:
            raise InvalidRequestError("missing rollout target trial_id")
        inf = self._running_inference_job(user_id, app, app_version)
        return self.rollouts.start(
            inf["id"], trial_id, canary_fraction=canary_fraction,
            batch=batch)

    def get_rollout_status(
        self, user_id: str, app: str, app_version: int = -1
    ) -> Dict:
        """The newest rollout of the app's current inference job (live
        phases carry the judge's per-lane signal snapshot)."""
        job = self.db.get_train_job_by_app_version(user_id, app, app_version)
        if job is None:
            raise InvalidRequestError(f"No such train job {app} v{app_version}")
        infs = self.db.get_inference_jobs_of_train_job(job["id"])
        for inf in infs:
            status = self.rollouts.status(inf["id"])
            if status is not None:
                return status
        raise InvalidRequestError(
            f"no rollout recorded for {app} v{job['app_version']}")

    def abort_rollout(
        self, user_id: str, app: str, app_version: int = -1
    ) -> Dict:
        inf = self._running_inference_job(user_id, app, app_version)
        return self.rollouts.abort(inf["id"])

    def ack_rollout(
        self, user_id: str, app: str, app_version: int = -1
    ) -> Dict:
        """Acknowledge the newest rolled-back rollout (clears the
        doctor WARN)."""
        job = self.db.get_train_job_by_app_version(user_id, app, app_version)
        if job is None:
            raise InvalidRequestError(f"No such train job {app} v{app_version}")
        infs = self.db.get_inference_jobs_of_train_job(job["id"])
        for inf in infs:
            try:
                return self.rollouts.ack(inf["id"])
            except InvalidRequestError:
                continue
        raise InvalidRequestError(
            f"no unacknowledged rollback for {app}")

    def get_drift_status(
        self, user_id: str, app: str, app_version: int = -1
    ) -> Dict:
        """The drift closed loop's state for the app's current inference
        job (admin/drift.py): phase, frozen-baseline flag, live signal
        snapshot, event tail."""
        if app_version == -1:
            # the drift row lives on the SERVING version's inference job;
            # a drift retrain's own (newer) train job never has one
            jobs = self.db.get_train_jobs_of_app(user_id, app)
            if not jobs:
                raise InvalidRequestError(f"No such app {app}")
        else:
            job = self.db.get_train_job_by_app_version(
                user_id, app, app_version)
            if job is None:
                raise InvalidRequestError(
                    f"No such train job {app} v{app_version}")
            jobs = [job]
        for job in jobs:
            for inf in self.db.get_inference_jobs_of_train_job(job["id"]):
                status = self.drift.status(inf["id"])
                if status is not None:
                    return status
        raise InvalidRequestError(
            f"no drift state recorded for {app}"
            + (f" v{app_version}" if app_version != -1 else ""))

    def ack_drift(
        self, user_id: str, app: str, app_version: int = -1
    ) -> Dict:
        """Acknowledge the app's drift loop: re-arms a PARKED loop or
        clears a rollback-flap streak (clears the doctor WARNs)."""
        inf = self._running_inference_job(user_id, app, app_version)
        return self.drift.ack(inf["id"])

    def _drop_predict_routes(self, inference_job_id: str) -> None:
        """Invalidate cached predict routes for a stopped inference job —
        within the TTL its workers may still be draining, so predict must
        go back to the control plane and correctly report the stop. Bumps
        the route epoch so an in-flight predict() that resolved before this
        stop cannot re-insert the dead route. Also prunes the job's relayed
        serving counters — a long-lived admin cycling many jobs must not
        accumulate entries for dead services forever."""
        with self._predict_route_lock:
            self._predict_route_epoch += 1
            for key, (_, predictor) in list(self._predict_route_cache.items()):
                if predictor._job_id == inference_job_id:
                    self._predict_route_cache.pop(key, None)
        workers = self.db.get_workers_of_inference_job(inference_job_id)
        with self._predict_route_lock:
            for w in workers:
                self._remote_serving_stats.pop(w["service_id"], None)

    def predict(
        self, user_id: str, app: str, queries: List[Any], app_version: int = -1
    ) -> List[Any]:
        """Serving entrypoint: route queries to the app's running predictor.

        The app->predictor resolution (two control-plane DB reads) is
        cached for a short TTL: the serving hot path must not convoy on the
        serialized metadata connection at high request rates, and a few
        seconds of staleness only delays visibility of a *newly swapped*
        inference job — a dead predictor raises and re-resolves
        immediately.

        Overload faults surface as typed exceptions the HTTP shell maps
        to shed codes (admin/http.py): QueueFullError /
        DeadlineUnmeetableError -> 429 + Retry-After,
        ServerOverloadedError -> 503."""
        from rafiki_tpu.cache.queue import QueueFullError
        from rafiki_tpu.predictor.admission import (
            DeadlineUnmeetableError,
            ServerOverloadedError,
        )

        key = (user_id, app, app_version)
        now = time.monotonic()
        with self._predict_route_lock:
            cached = self._predict_route_cache.get(key)
        if cached is not None and now - cached[0] < config.PREDICT_ROUTE_TTL_S:
            try:
                return self._admitted_predict(cached[1], queries, tenant=app)
            except (QueueFullError, ServerOverloadedError,
                    DeadlineUnmeetableError):
                # overload shed, not a dead route: re-resolving would only
                # add two control-plane reads to an already-loaded path
                raise
            except TimeoutError:
                # SLO missed. Drop the route (it MAY be stale) but do NOT
                # resubmit: under overload a timeout is the common outcome,
                # and a silent second full-length attempt doubles queue
                # pressure and pins the handler for 2x PREDICT_TIMEOUT_S —
                # retry policy belongs to the client, which just got a 504.
                with self._predict_route_lock:
                    self._predict_route_cache.pop(key, None)
                raise
            except RuntimeError:
                # workers gone (job stopped/replaced): fall through and
                # re-resolve against the control plane
                with self._predict_route_lock:
                    self._predict_route_cache.pop(key, None)
        with self._predict_route_lock:
            epoch = self._predict_route_epoch
        if app_version == -1:
            # serving resolution, not catalog resolution: skip versions
            # with no running inference job (e.g. a drift auto-retrain's
            # own train job, which bumps the version but never deploys)
            jobs = self.db.get_train_jobs_of_app(user_id, app)
            if not jobs:
                raise InvalidRequestError(f"No such app {app}")
            inf = next(
                (i for i in (
                    self.db.get_running_inference_job_of_train_job(j["id"])
                    for j in jobs) if i is not None), None)
        else:
            job = self.db.get_train_job_by_app_version(
                user_id, app, app_version)
            if job is None:
                raise InvalidRequestError(f"No such app {app}")
            inf = self.db.get_running_inference_job_of_train_job(job["id"])
        if inf is None:
            raise InvalidRequestError("No running inference job for this app")
        predictor = self.services.get_predictor(inf["id"])
        if predictor is None:
            raise InvalidRequestError("Predictor not available")
        with self._predict_route_lock:
            # only cache if no invalidation ran while we resolved — a
            # concurrent stop_inference_job must not have its route
            # resurrected by this thread's stale resolution
            if self._predict_route_epoch == epoch:
                self._predict_route_cache[key] = (now, predictor)
        return self._admitted_predict(predictor, queries, tenant=app)

    def _admitted_predict(self, predictor, queries: List[Any],
                          tenant: Optional[str] = None) -> List[Any]:
        """The admin door's admission wrapper: bounded in-flight +
        estimated-wait shed before the predictor sees the request, and
        latency feedback after (predictor/admission.py)."""
        cap = int(config.PREDICT_QUEUE_DEPTH)
        if cap > 0 and len(queries) > cap:
            # can never fit in any worker queue: permanent client error,
            # not the retryable 429
            raise InvalidRequestError(
                f"request carries {len(queries)} queries but the "
                f"per-worker queue cap is {cap} "
                "(RAFIKI_PREDICT_QUEUE_DEPTH) — split the request")
        backlog_fn = getattr(predictor, "backlog_depth", None)
        # tenant = the app: the admin door is SHARED across jobs, so this
        # is where one hot job saturating its weighted fair share gets
        # 429s while cold jobs keep their latency (RAFIKI_AUTOSCALE_FAIR).
        # With the prediction cache on, cost is the MISSES-ONLY estimate
        # (predictor/result_cache.py) — cache hits shed no load, so the
        # fairness book charges only what will reach a worker.
        cost_fn = getattr(predictor, "admission_cost", None)
        cost = cost_fn(queries) if callable(cost_fn) else len(queries)
        self._predict_admission.admit(
            config.PREDICT_TIMEOUT_S,
            backlog_depth=backlog_fn() if callable(backlog_fn) else None,
            tenant=tenant, cost=cost)
        t0 = time.monotonic()
        try:
            preds = predictor.predict_batch(queries)
        finally:
            self._predict_admission.release(tenant=tenant)
        self._predict_admission.observe(time.monotonic() - t0, len(queries))
        return preds

    def get_fleet_health(self) -> Dict[str, Any]:
        """Operator view of the fleet health subsystem: per-agent
        heartbeat state, circuit breaker state, and load
        (placement/hosts.py agent_health). Single-host placements report
        an empty agent map — the admin process itself answering IS the
        health signal there.

        The ``serving`` section is the overload picture (docs/
        failure-model.md "Overload faults"): per-job queue depths and
        hedge-suppression counters from each live Predictor — a job with
        zero registered worker queues reads ``degraded``, the admin-side
        twin of the per-job /healthz verdict — plus this door's admission
        stats and the local workers' queue counters (SERVING_STATS)."""
        from rafiki_tpu.utils import chaos as _chaos
        from rafiki_tpu.worker.inference import serving_stats

        agents = {}
        if hasattr(self.placement, "agent_health"):
            agents = self.placement.agent_health()
        down = [a for a, h in agents.items() if h["state"] == "DOWN"]
        jobs: Dict[str, Any] = {}
        predictors = self.services.predictors()
        for job_id, predictor in predictors.items():
            try:
                depths = predictor.queue_depths()
                jobs[job_id] = {
                    "status": "ok" if depths else "degraded",
                    "workers": len(depths),
                    "queue_depths": depths,
                    "overload": predictor.overload_stats(),
                }
            except Exception:
                logger.exception("fleet-health probe of job %s failed",
                                 job_id)
        # local workers update SERVING_STATS in-process; process/hosts
        # placement workers relay the same counters over the event channel
        # (handle_event inference_worker_stats) — merge both so the
        # overload picture covers every deployment mode
        workers = serving_stats()
        with self._predict_route_lock:
            for sid, s in self._remote_serving_stats.items():
                workers.setdefault(sid, {}).update(s)
        # per-replica warm state (worker/warmup.py): cold/warm verdict +
        # last-boot compile seconds. Local workers' reports are read
        # directly; process/hosts workers relay the same fields on their
        # stats rows (merged above).
        from rafiki_tpu.worker.warmup import stats_row_fields, warmup_stats

        for sid in list(warmup_stats()):
            workers.setdefault(sid, {}).update(stats_row_fields(sid))
        # generative serving picture, aggregated per job (the workers'
        # rows carry their job id): the paged-KV pool footprint and the
        # per-tenant prefix-cache hit rates the shared-prefix lever is
        # judged by (docs/serving-generation.md)
        generation: Dict[str, Any] = {}
        for s in workers.values():
            job = s.get("gen_job")
            if not job:
                continue
            g = generation.setdefault(job, {
                "workers": 0, "slots_busy": 0, "tokens": 0,
                "kv_blocks_used": 0, "kv_pool_blocks": 0,
                "prefix_hits": 0, "prefix_misses": 0,
                "prefix_hit_tokens": 0,
                "spec_workers": 0, "spec_proposed": 0,
                "spec_accepted": 0, "spec_rounds": 0,
                "spec_degraded": [], "resident_streams": 0,
            })
            g["workers"] += 1
            g["slots_busy"] += int(s.get("gen_slots_busy", 0))
            g["resident_streams"] += int(s.get("gen_resident_streams", 0))
            g["tokens"] += int(s.get("gen_tokens", 0))
            g["kv_blocks_used"] += int(s.get("gen_kv_blocks_used", 0))
            g["kv_pool_blocks"] += int(s.get("gen_kv_pool_blocks", 0))
            g["prefix_hits"] += int(s.get("gen_prefix_hits", 0))
            g["prefix_misses"] += int(s.get("gen_prefix_misses", 0))
            g["prefix_hit_tokens"] += int(
                s.get("gen_prefix_hit_tokens", 0))
            # speculative decoding picture (worker/generation.py): the
            # acceptance rate is the lever's health signal — a low rate
            # means the draft earns its k forward passes back rarely
            g["spec_workers"] += 1 if s.get("gen_spec_on") else 0
            g["spec_proposed"] += int(s.get("gen_spec_proposed", 0))
            g["spec_accepted"] += int(s.get("gen_spec_accepted", 0))
            g["spec_rounds"] += int(s.get("gen_spec_rounds", 0))
            if s.get("gen_spec_degraded"):
                g["spec_degraded"].append(str(s["gen_spec_degraded"]))
        for g in generation.values():
            admitted = g["prefix_hits"] + g["prefix_misses"]
            g["prefix_hit_rate"] = (
                round(g["prefix_hits"] / admitted, 3) if admitted
                else None)
            g["spec_acceptance_rate"] = (
                round(g["spec_accepted"] / g["spec_proposed"], 3)
                if g["spec_proposed"] else None)
        # stream-continuity rollup (docs/failure-model.md "Stream
        # continuity"): the door-side journal/resume picture per gen job
        # — resumes by trigger, client-visible continuity losses, and
        # the journal's occupancy — merged from each job's Predictor
        for job_id, g in generation.items():
            predictor = predictors.get(job_id)
            cont_fn = getattr(predictor, "gen_continuity_stats", None)
            if callable(cont_fn):
                try:
                    g["continuity"] = cont_fn()
                except Exception:
                    logger.exception(
                        "continuity probe of job %s failed", job_id)
        # training-plane fault picture (docs/failure-model.md,
        # "Training-plane faults"): per-job fault-kind counters and
        # absorbed retries from the STORE (covers every placement mode),
        # plus in-process worker counters (quarantined signatures,
        # re-proposals, feedback drops) from worker/faults.py
        from rafiki_tpu.constants import TrainJobStatus as _TJS
        from rafiki_tpu.worker.faults import training_stats as _tstats

        train_jobs: Dict[str, Any] = {}
        try:
            summary = self.db.get_trial_fault_summary_of_live_jobs()
            for j in self.db.get_train_jobs_by_statuses(
                    [_TJS.STARTED, _TJS.RUNNING]):
                entry = summary.get(j["id"], {"faults": {}, "retries": 0})
                train_jobs[j["id"]] = {"status": j["status"], **entry}
        except Exception:
            logger.exception("fleet-health training scan failed")
        return {
            "placement": type(self.placement).__name__,
            "agents": agents,
            "agents_down": down,
            "chaos_active": _chaos.enabled(),
            # boot-reconciliation outcome (admin/recovery.py): state is
            # `recovering` while the off-thread pass runs — the HTTP
            # doors 503 until it reads `ready`
            "recovery": self.recovery_status(),
            # control-plane HA (admin/lease.py): leadership role, epoch,
            # lease validity — `enabled: False` when running solo
            "ha": ({"enabled": True, **self._lease.status()}
                   if self._lease is not None else {"enabled": False}),
            # closed-loop overload adaptation (admin/autoscaler.py):
            # loop state, chip-loan picture, recent scale decisions with
            # their reason + signal snapshot
            "autoscaler": self.autoscaler.report(),
            # warm standby pool (admin/warm_pool.py): per-job standby
            # counts, degraded pools, loan split, recent pool events
            "warm_pool": self.warm_pool.report(),
            # safe live rollouts (admin/rollout.py): in-flight rollouts
            # with the judge's live per-lane signals, plus recent events
            # (rollback reasons + the signal snapshots they fired on)
            "rollouts": self.rollouts.report(),
            # drift closed loop (admin/drift.py): per-job phase +
            # divergence signal snapshot, plus the recent event tail
            # (drift verdicts, retrain launches, rollout outcomes)
            "drift": self.drift.report(),
            "serving": {
                "jobs": jobs,
                "admission": self._predict_admission.stats(),
                # per-tenant decayed admitted-query charges at this door
                # (weighted fair admission, RAFIKI_AUTOSCALE_FAIR)
                "fair_shares": self._predict_admission.fair_shares(),
                "workers": workers,
                # per-job generative picture: paged-KV pool footprint +
                # prefix-cache hit rates (worker/kv_paging.py)
                "generation": generation,
                # prediction result cache + single-flight picture
                # (predictor/result_cache.py): bounds, occupancy, and
                # per-tenant hit rates
                "prediction_cache": self._prediction_cache_stats(),
            },
            "training": {
                "jobs": train_jobs,
                "workers": _tstats(),
            },
        }

    @staticmethod
    def _prediction_cache_stats() -> Dict[str, Any]:
        from rafiki_tpu.predictor.result_cache import get_cache

        try:
            return get_cache().stats()
        # lint: absorb(fleet health must answer even when the cache probe faults)
        except Exception:
            logger.exception("prediction-cache stats probe failed")
            return {}

    def stop_all_jobs(self) -> None:
        """Stop every running train/inference job (reference client
        stop_all_jobs, rafiki/client/client.py:647), marking the job rows —
        not just their services — so job state stays consistent."""
        for inf in self.db.get_inference_jobs_by_statuses(
            [InferenceJobStatus.STARTED, InferenceJobStatus.RUNNING]
        ):
            self.rollouts.abort_for_job(inf["id"], "stop_all_jobs")
            self.services.stop_inference_services(inf["id"])
            self._drop_predict_routes(inf["id"])
        for job in self.db.get_train_jobs_by_statuses(
            [TrainJobStatus.STARTED, TrainJobStatus.RUNNING]
        ):
            self.services.stop_train_services(job["id"])
            self.db.mark_train_job_as_stopped(job["id"])
        # sweep any stragglers (e.g. services of already-errored jobs) —
        # the status filter runs in SQL against idx_service_status, not
        # as a full-table python sweep
        for svc in self.db.get_services(
                statuses=["STARTED", "DEPLOYING", "RUNNING"]):
            self.services._destroy_service(svc["id"], wait=False)

    # -- events ------------------------------------------------------------------

    def handle_event(self, name: str, payload: Dict[str, Any]) -> None:
        """Worker events drive job status (reference admin.py:595-616)."""
        try:
            if name == EVENT_BUDGET_REACHED:
                # Graceful drain: each worker exits on its own once the shared
                # budget is consumed (the reference instead destroyed the
                # sub-job's containers, terminating peers mid-trial and
                # discarding their work, reference admin.py:607). Nothing to
                # kill — just fold the exit into job status.
                self.services.refresh_train_job_status(payload["train_job_id"])
            elif name == EVENT_TRIAL_FAULT_LIMIT:
                # Job fail-fast (trial fault taxonomy): a worker hit
                # RAFIKI_TRIAL_FAULT_LIMIT consecutive user-class trial
                # faults — the template is broken, so the job errors NOW
                # with the typed reason instead of grinding its budget.
                # The worker already marked the row (works headless);
                # the guarded transition makes this a no-op then. Tear
                # down sibling workers — they are failing the same way.
                self.db.mark_train_job_as_errored(
                    payload["train_job_id"],
                    payload.get("fault_kind"),
                    payload.get("reason"))
                self.services.stop_train_services(payload["train_job_id"])
            elif name in ("train_job_worker_started", "train_job_worker_stopped"):
                self.services.refresh_train_job_status(payload["train_job_id"])
            elif name == "service_status":
                # forwarded by per-host placement agents (placement/agent.py)
                # so job-level refresh fires even for remotely-placed workers
                self._on_service_status(payload["service_id"], payload["status"])
            elif name == "inference_worker_stats":
                # serving counters from OUT-OF-PROCESS inference workers
                # (process placement) — in-process workers update the local
                # SERVING_STATS module dict directly
                sid = payload["service_id"]
                # compound insert+move+evict must be atomic vs the API
                # threads reading/pruning this dict (GIL atomicity only
                # covers single C-level dict ops)
                with self._predict_route_lock:
                    self._remote_serving_stats[sid] = {
                        "batches": int(payload.get("batches", 0)),
                        "queries": int(payload.get("queries", 0)),
                        # overload counters ride the same event when the
                        # worker's queue exposes them (queue_depth gauge,
                        # expired/shed totals); paged-KV generation
                        # workers add the block-pool + prefix-cache
                        # picture fleet health aggregates per job
                        **{k: int(payload[k])
                           for k in ("queue_depth", "expired", "shed",
                                     "gen_slots_busy", "gen_slots_max",
                                     "gen_kv_blocks_used",
                                     "gen_kv_pool_blocks",
                                     "gen_kv_block_tokens",
                                     "gen_prefix_hits",
                                     "gen_prefix_misses",
                                     "gen_prefix_hit_tokens",
                                     "gen_spec_proposed",
                                     "gen_spec_accepted",
                                     "gen_spec_rounds")
                           if k in payload},
                        **{k: payload[k]
                           for k in ("gen_spec_on",)
                           if k in payload},
                        **({"gen_job": str(payload["gen_job"])}
                           if "gen_job" in payload else {}),
                        **({"gen_spec_degraded":
                            str(payload["gen_spec_degraded"])}
                           if "gen_spec_degraded" in payload else {}),
                    }
                    self._remote_serving_stats.move_to_end(sid)
                    while (len(self._remote_serving_stats)
                           > self._remote_serving_stats_cap):
                        self._remote_serving_stats.popitem(last=False)
                if "gen_slots_busy" in payload:
                    # the autoscaler's generative load signal lives in
                    # THIS process's registry; a process-placed
                    # generation worker's occupancy reaches it through
                    # this relay (in-process workers record the ring
                    # directly — same name, so the reader can't tell).
                    # Under the paged layout the binding resource is the
                    # BLOCK POOL, so its fraction is the signal; ring
                    # workers keep reporting busy slots.
                    worker_row = self.db.get_inference_job_worker(sid)
                    if "gen_kv_pool_blocks" in payload:
                        pool = max(int(payload["gen_kv_pool_blocks"]), 1)
                        occupancy = int(
                            payload.get("gen_kv_blocks_used", 0)) / pool
                    else:
                        slots_max = max(
                            int(payload.get("gen_slots_max", 1)), 1)
                        occupancy = int(
                            payload["gen_slots_busy"]) / slots_max
                    if worker_row is not None:
                        from rafiki_tpu.utils.metrics import REGISTRY

                        REGISTRY.ring(
                            "slot_occupancy:job:"
                            f"{worker_row['inference_job_id']}").record(
                            occupancy)
        except Exception:
            logger.exception("event %s failed", name)

    def _on_service_status(self, service_id: str, status: str) -> None:
        if status == "RUNNING":
            self.db.mark_service_as_running(service_id)
        elif status == "STOPPED":
            self.db.mark_service_as_stopped(service_id)
        elif status == "ERRORED":
            self.db.mark_service_as_errored(service_id)
        if status in ("STOPPED", "ERRORED"):
            # a dying replica's chip loan comes home however it died —
            # heartbeat-detected host death never reaches the
            # ServicesManager teardown chokepoint (idempotent pop;
            # getattr: status events can predate arbiter wiring at boot)
            arbiter = getattr(self, "chip_arbiter", None)
            if arbiter is not None:
                arbiter.note_return(service_id)
        # a train worker stopping may complete its train job
        worker = self.db.get_train_job_worker(service_id)
        if worker is not None and status in ("STOPPED", "ERRORED"):
            sub = self.db.get_sub_train_job(worker["sub_train_job_id"])
            if sub is not None:
                self.services.refresh_train_job_status(sub["train_job_id"])
        # the last serving replica dying must terminate its inference job
        # (fleet health: dead-host workers are errored by the heartbeat
        # monitor, placement/hosts.py) — and its cached predict routes
        if worker is None and status in ("STOPPED", "ERRORED"):
            iworker = self.db.get_inference_job_worker(service_id)
            if iworker is not None:
                if status == "ERRORED":
                    # zero-deploy replacement: a dead ROUTABLE replica is
                    # replaced from the warm standby pool immediately (an
                    # add_worker route); the pool's next tick replenishes
                    pool = getattr(self, "warm_pool", None)
                    if pool is not None:
                        try:
                            pool.on_replica_errored(
                                service_id, iworker["inference_job_id"])
                        # lint: absorb(replacement is a fast-path optimization: the job-status refresh below still runs either way)
                        except Exception:
                            logger.exception(
                                "warm-pool replacement for %s failed",
                                service_id[:8])
                final = self.services.refresh_inference_job_status(
                    iworker["inference_job_id"])
                if final is not None:
                    self._drop_predict_routes(iworker["inference_job_id"])

    def shutdown(self) -> None:
        # the autoscaler must stop deciding before services are torn down
        # — a tick racing the teardown would re-place replicas
        if getattr(self, "autoscaler", None) is not None:
            self.autoscaler.stop()
        # the warm pool likewise: a top-up racing the teardown would
        # place standbys nothing will ever stop
        if getattr(self, "warm_pool", None) is not None:
            self.warm_pool.stop()
        # the drift loop must stop deciding before the rollout
        # controller it drives — a tick racing the teardown could start
        # a rollout nothing will ever judge
        if getattr(self, "drift", None) is not None:
            self.drift.stop()
        # rollout runs likewise: a mid-flight placement racing the
        # teardown would resurrect a replica nothing will ever stop
        if getattr(self, "rollouts", None) is not None:
            self.rollouts.stop()
        # a reconcile racing a shutdown would resurrect services the stop
        # below is about to tear down: signal it to ABORT (it checks at
        # every loop top and inside retry backoffs), then join it out
        if self._recovery_runner is not None:
            self._recovery_runner.abort()
        if self._recovery_thread is not None:
            self._recovery_thread.join(timeout=30)
        try:
            self.stop_all_jobs()
        except (StaleEpochError, StaleAdminEpochError) as e:
            # a fenced ex-leader has nothing left to tear down — the new
            # leader adopted the fleet; forcing the teardown through would
            # be exactly the double-teardown the fence exists to stop
            logger.warning("shutdown teardown skipped (fenced): %s", e)
        if hasattr(self.placement, "stop_all"):
            self.placement.stop_all()
        # the shm broker holds listener threads + /dev/shm segments; the
        # in-process broker has no close()
        close = getattr(self.broker, "close", None)
        if close is not None:
            close()
        # last: releasing the lease clears the fences, so every mutation
        # above still ran under epoch protection
        if self._lease is not None:
            self._lease.stop(release=True)
