"""Standalone admin server: `python -m rafiki_tpu.admin`.

The analogue of the reference's `scripts/start_admin.py` (seed superadmin,
serve the REST API until signalled). Config via env:

    RAFIKI_WORKDIR      data/params/logs/db root      (default: cwd)
    RAFIKI_DB_PATH      store file                    (default: WORKDIR/rafiki.sqlite3)
    RAFIKI_ADMIN_HOST   bind host                     (default: 127.0.0.1)
    RAFIKI_ADMIN_PORT   bind port                     (default: 3000; 0 = ephemeral)
    RAFIKI_PLACEMENT    local | process               (default: local)
    RAFIKI_BROKER       shm for the native data plane (forced by process mode)

With RAFIKI_PLACEMENT=process, train/inference workers run as child
*processes* with chip grants, shared SQLite/WAL metadata, shm serving
queues, and HPO coordination back through this server's REST API — the
single-host deployment story the reference delivered with Docker Swarm
(reference scripts/start.sh:1-25, docs/src/dev/architecture.rst:17-48).
"""

from __future__ import annotations

import logging
import os
import signal
import sys
import threading


def main() -> int:
    logging.basicConfig(
        level=os.environ.get("RAFIKI_LOG_LEVEL", "INFO"),
        format="%(levelname)s:%(asctime)s:%(name)s: %(message)s",
    )
    from rafiki_tpu import config
    from rafiki_tpu.admin.admin import Admin
    from rafiki_tpu.admin.http import AdminServer
    from rafiki_tpu.db.database import Database

    for sub in ("data", "params", "logs"):
        os.makedirs(os.path.join(config.WORKDIR, sub), exist_ok=True)

    admin = Admin(db=Database(config.DB_PATH))
    host = os.environ.get("RAFIKI_ADMIN_HOST", "127.0.0.1")
    port = int(os.environ.get("RAFIKI_ADMIN_PORT", "3000"))
    server = AdminServer(admin, host=host, port=port).start()
    placement = type(admin.placement).__name__
    rec = admin.recovery_status()
    rec_note = ("" if rec.get("state") == "ready" and not rec.get("scanned")
                else f", recovery={rec.get('state')}")
    print(f"rafiki_tpu admin on http://{host}:{server.port} "
          f"(db={admin.db.path}, placement={placement}{rec_note})",
          flush=True)

    stop = threading.Event()
    signal.signal(signal.SIGTERM, lambda *a: stop.set())
    signal.signal(signal.SIGINT, lambda *a: stop.set())
    try:
        stop.wait()
    finally:
        print("shutting down...", flush=True)
        server.stop()
        admin.shutdown()
    return 0


if __name__ == "__main__":
    sys.exit(main())
