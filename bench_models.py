"""Flagship-model benchmarks: step time, throughput, and MFU on the live
backend.

Fills the BASELINE.md "Measured TPU baselines" rows the AutoML bench can't:
ViT-B/16 (the BASELINE.json north-star config) and the progressive GAN (the
reference fork's marquee model, reference pg_gans.py).

MFU accounting (VERDICT r2 item 1): FLOPs are counted *analytically* —
matmul/conv multiply-adds at 2 FLOPs each, backward = 2x forward — the
PaLM-style model-FLOPs convention. XLA's ``cost_analysis()`` is NOT used
for MFU: it counts a ``lax.scan`` body once regardless of trip count, which
under-reported the ViT step ~6x in round 2 (0.59 vs ~6.7 TFLOP at bs=64).
It is still reported as ``xla_cost_analysis_tflops`` for cross-checking.

Timing: each measured call runs ``steps_per_call`` train steps inside one
jitted ``lax.scan`` with params/opt_state donated, and synchronizes by
fetching the final loss to the host. Through the remote-chip tunnel this
matters a great deal: a device->host sync costs ~15-20 ms, and
``block_until_ready`` alone does not actually fence execution on this
platform — round 2's per-step timing was dispatch-bound, not compute-bound.

Run standalone (`python bench_models.py`) for a JSON report, or let
bench.py embed the numbers in its one-line summary (RAFIKI_BENCH_MODELS=0
skips).
"""

from __future__ import annotations

import json
import os
import time
from typing import Any, Dict, Optional

import numpy as np

# v5e: 197 TFLOP/s bf16 per chip (public spec); override for other parts
PEAK_TFLOPS = float(os.environ.get("RAFIKI_PEAK_TFLOPS", "197"))


def vit_train_flops(cfg, batch_size: int) -> float:
    """Analytic model-FLOPs of one ViT train step (fwd + bwd + no optimizer
    matmuls), counting each multiply-add as 2 FLOPs and backward as 2x
    forward. Matmul/conv terms only — elementwise/softmax/LN are noise next
    to the MXU work and inflating MFU with them would flatter the number."""
    S, D = cfg.seq_len, cfg.encoder.dim
    mlp_hidden = cfg.encoder.mlp_ratio * D
    per_block = (
        8 * S * D * D          # qkv + output projections
        + 4 * S * S * D        # scores (q@k) + weighted values (p@v)
        + 4 * S * D * mlp_hidden  # mlp in + out
    )
    patch = 2 * S * D * (cfg.patch_size ** 2 * cfg.channels)
    head = 2 * D * cfg.num_classes
    fwd = cfg.encoder.depth * per_block + patch + head
    return 3.0 * fwd * batch_size


def _xla_flops(jitted, *args) -> Optional[float]:
    """XLA's own FLOP estimate (cross-check only — undercounts scan)."""
    try:
        compiled = jitted.lower(*args).compile()
        analysis = compiled.cost_analysis()
        if isinstance(analysis, list):  # per-device list on some backends
            analysis = analysis[0]
        flops = float(analysis.get("flops", 0.0))
        return flops if flops > 0 else None
    except Exception:
        return None


def bench_vit(batch_size: int = 192, image_size: int = 224,
              n_steps: int = 32, steps_per_call: int = 8,
              remat: Optional[str] = "dots",
              scan_unroll: int = 1,
              use_flash: Optional[bool] = None,
              mu_bf16: bool = False,
              fused_qkv: bool = False) -> Dict[str, Any]:
    """ViT-B/16 fused train step (fwd+bwd+adamw), bf16 activations, donated
    buffers, multi-step scan per dispatch, dots-saveable remat (batches
    this size do not fit 16 GB HBM with full activation stashing).
    Batch 192 is the measured single-chip optimum (swept 128/192/224/256:
    0.350/0.355/0.324/0.330 MFU). ``scan_unroll`` unrolls the depth scan
    so XLA can fuse across blocks (see TransformerConfig.scan_unroll).
    ``use_flash`` forces the attention kernel at seq 197 (None = the
    footprint auto-dispatch, which picks XLA fused attention here);
    ``mu_bf16`` keeps adamw's first moment in bf16 — halves the largest
    optimizer-state HBM stream (verdict r5: levers beyond the r3 grid)."""
    import dataclasses

    import jax
    import jax.numpy as jnp
    import optax

    from rafiki_tpu.models import vit

    cfg = vit.vit_b16(num_classes=1000, image_size=image_size)
    cfg = dataclasses.replace(
        cfg, encoder=dataclasses.replace(
            cfg.encoder, remat=remat, scan_unroll=scan_unroll,
            use_flash=use_flash, fused_qkv=fused_qkv))
    params = jax.jit(lambda r: vit.init(r, cfg))(jax.random.key(0))
    opt = optax.adamw(
        1e-3, mu_dtype=jnp.bfloat16 if mu_bf16 else None)
    opt_state = jax.jit(opt.init)(params)

    # bf16 inputs: the model computes in bf16 anyway (core.cast_for_compute);
    # shipping f32 just doubles the input HBM traffic
    x = jnp.zeros((batch_size, image_size, image_size, 3), jnp.bfloat16)
    y = jnp.zeros((batch_size,), jnp.int32)

    def loss_fn(p, batch, rng):
        xx, yy = batch
        logits = vit.apply(p, xx, cfg, rng, deterministic=False)
        return optax.softmax_cross_entropy_with_integer_labels(logits, yy).mean()

    def one_step(carry, _):
        p, s, rng = carry
        rng, sub = jax.random.split(rng)
        loss, grads = jax.value_and_grad(loss_fn)(p, (x, y), sub)
        updates, s = opt.update(grads, s, p)
        return (optax.apply_updates(p, updates), s, rng), loss

    def multi_step(p, s, rng):
        (p, s, rng), losses = jax.lax.scan(
            one_step, (p, s, rng), None, length=steps_per_call)
        return p, s, rng, losses

    jitted = jax.jit(multi_step, donate_argnums=(0, 1))
    xla_flops = _xla_flops(jitted, params, opt_state, jax.random.key(2))

    rng = jax.random.key(2)
    # warmup (compile + first dispatch); fetching the loss value is the only
    # reliable execution fence through the tunnel
    params, opt_state, rng, losses = jitted(params, opt_state, rng)
    _ = float(losses[-1])

    n_calls = max(n_steps // steps_per_call, 1)
    t0 = time.perf_counter()
    for _ in range(n_calls):
        params, opt_state, rng, losses = jitted(params, opt_state, rng)
    _ = float(losses[-1])
    step_s = (time.perf_counter() - t0) / (n_calls * steps_per_call)

    flops = vit_train_flops(cfg, batch_size)
    out = {
        "model": "ViT-B/16",
        "batch_size": batch_size,
        "remat": remat,
        "scan_unroll": scan_unroll,
        "use_flash": use_flash,
        "mu_bf16": mu_bf16,
        "fused_qkv": fused_qkv,
        "steps_per_call": steps_per_call,
        "step_time_ms": round(step_s * 1000, 2),
        "steps_per_s": round(1.0 / step_s, 3),
        "images_per_s": round(batch_size / step_s, 1),
        "backend": jax.default_backend(),
        "step_tflops_analytic": round(flops / 1e12, 3),
        "mfu": round(flops / (step_s * PEAK_TFLOPS * 1e12), 4),
        "mfu_note": ("analytic matmul FLOPs (2*MAC, bwd=2x fwd) / "
                     f"{PEAK_TFLOPS:.0f} TFLOP/s peak"),
    }
    if xla_flops is not None:
        # cross-check only: cost_analysis counts each lax.scan body ONCE,
        # so its count for this program (an outer steps_per_call-step scan
        # whose body contains the depth-layer scan) must be scaled by both
        # trip counts before comparing to the per-step analytic number.
        # The reconciliation is printed so a reader can verify the 11x-ish
        # raw gap is scan accounting, not a FLOP miscount (VERDICT r3
        # weak #3).
        depth = cfg.encoder.depth
        eff_unroll = max(min(scan_unroll, depth), 1)
        scanned_iters = depth // eff_unroll
        reconciled = xla_flops * scanned_iters
        out["xla_cost_analysis_tflops"] = round(xla_flops / 1e12, 3)
        out["xla_reconciliation"] = (
            f"cost_analysis counts scan bodies once: raw {xla_flops/1e12:.3f}"
            f" TFLOP covers 1 of {steps_per_call} outer steps and "
            f"{eff_unroll} of {depth} layers -> x{scanned_iters} layer iters"
            f" ~= {reconciled/1e12:.3f} TFLOP/step vs analytic "
            f"{flops/1e12:.3f} (residual = optimizer/patchify/head + "
            f"per-call constants)")
    return out


def bench_pggan(resolution: int = 64, minibatch: int = 128,
                n_steps: int = 20) -> Dict[str, Any]:
    """Progressive-GAN D+G step at full resolution (the steady-state cost
    once growth completes — the reference's headline img/s regime).
    Minibatch 128 is the measured single-chip optimum (swept 64/128/256:
    0.374/0.459/0.427 MFU).

    MFU here uses XLA's ``cost_analysis`` of the two compiled steps: unlike
    the ViT bench (whose ``lax.scan`` bodies cost_analysis counts once),
    the PGGAN graph unrolls its stage loop in Python, so the compiler's
    count is the true per-execution FLOPs."""
    import jax
    import jax.numpy as jnp

    from rafiki_tpu.models import pggan

    cfg = pggan.PgganConfig(resolution=resolution)
    trainer = pggan.PgganTrainer(cfg)
    trainer.init_optimizers(1e-3, 1e-3)
    max_stage = cfg.num_stages - 1
    d_step, g_step = trainer._get_steps(max_stage, minibatch)
    reals = jnp.zeros((minibatch, resolution, resolution, 3), jnp.float32)
    lod = jnp.float32(0.0)
    state = {"rng": jax.random.PRNGKey(0)}

    kd0, kg0 = jax.random.split(jax.random.PRNGKey(1))
    d_flops = _xla_flops(d_step, trainer.d_params, trainer.g_params,
                         trainer._opt_state["d"], reals, None, lod, kd0)
    g_flops = _xla_flops(g_step, trainer.g_params, trainer.d_params,
                         trainer._opt_state["g"], None, lod, kg0)

    def one():
        state["rng"], kd, kg = jax.random.split(state["rng"], 3)
        trainer.d_params, trainer._opt_state["d"], d_loss, _ = d_step(
            trainer.d_params, trainer.g_params, trainer._opt_state["d"],
            reals, None, lod, kd)
        trainer.g_params, trainer._opt_state["g"], g_loss = g_step(
            trainer.g_params, trainer.d_params, trainer._opt_state["g"],
            None, lod, kg)
        return g_loss

    _ = float(one())  # warmup: compiles both D and G directions
    t0 = time.perf_counter()
    last = None
    for _ in range(n_steps):
        last = one()
    _ = float(last)  # execution fence (see module docstring)
    step_s = (time.perf_counter() - t0) / n_steps
    out = {
        "model": f"PGGAN-{resolution}",
        "minibatch": minibatch,
        "step_time_ms": round(step_s * 1000, 2),
        "images_per_s": round(minibatch / step_s, 1),
        "kimg_per_hour": round(minibatch / step_s * 3.6, 1),
        "backend": jax.default_backend(),
    }
    if d_flops is not None and g_flops is not None:
        flops = d_flops + g_flops
        out["step_tflops_xla"] = round(flops / 1e12, 3)
        out["mfu"] = round(flops / (step_s * PEAK_TFLOPS * 1e12), 4)
        out["mfu_note"] = ("XLA cost_analysis FLOPs (exact: no scan in this "
                           f"graph) / {PEAK_TFLOPS:.0f} TFLOP/s peak")
    return out


def run_all(small: bool = False) -> Dict[str, Any]:
    """All flagship benches; ``small`` shrinks shapes for CPU smoke."""
    if small:
        return {
            "vit": bench_vit(batch_size=4, image_size=64, n_steps=4,
                             steps_per_call=2),
            "pggan": bench_pggan(resolution=16, minibatch=8, n_steps=3),
        }
    return {
        "vit": bench_vit(),
        "pggan": bench_pggan(),
    }


def sweep_pggan() -> None:
    """PGGAN minibatch sweep (the r3 optimum 128 was swept by hand);
    one JSON line per config, crash-safe. Grid: RAFIKI_SWEEP_MINIBATCH."""
    minibatches = [int(m) for m in os.environ.get(
        "RAFIKI_SWEEP_MINIBATCH", "64,128,256").split(",")]
    best = None
    for mb in minibatches:
        tag = {"minibatch": mb}
        try:
            r = bench_pggan(minibatch=mb)
        except Exception as e:
            print(json.dumps({**tag, "error": repr(e)[:300]}), flush=True)
            continue
        print(json.dumps({**tag, "mfu": r.get("mfu"),
                          "images_per_s": r["images_per_s"]}), flush=True)
        # rank by throughput: per-image FLOPs are fixed across minibatch,
        # so images/s orders identically to MFU and stays comparable even
        # when cost_analysis yields no MFU for some config
        if best is None or r["images_per_s"] > best[1]["images_per_s"]:
            best = (tag, r)
    if best is not None:
        print(json.dumps({"best": best[0], "result": best[1]}), flush=True)


def bench_longctx(seqs=(2048, 4096, 8192), b: int = 4, h: int = 12,
                  dh: int = 64, n_steps: int = 8) -> None:
    """Long-context attention fwd+bwd: XLA fused vs the pallas flash
    kernel at each sequence length, one JSON line per config (the
    BASELINE long-context row was a one-off session script in r3; this
    makes it reproducible). An XLA failure at long seq (the (S,S) score
    tensors exceed HBM — through the tunnel it surfaces as a
    remote_compile 500) is RECORDED, not fatal: that asymmetry is the
    point of the flash kernel. Tile shapes come from
    RAFIKI_FLASH_BLOCK_Q/_K read HERE and passed explicitly — the
    production kernel's defaults stay untouched. Flash runs FIRST at
    each seq: the XLA long-seq attempt is the one expected to fail, and
    on a sick tunnel it can hang and eat the script budget — the flash
    rows (the datapoints this bench exists for) must already be out."""
    import functools

    import jax
    import jax.numpy as jnp
    from jax import lax

    from rafiki_tpu.ops import flash_attention, mha_reference

    block_q = int(os.environ.get("RAFIKI_FLASH_BLOCK_Q", "128"))
    block_k = int(os.environ.get("RAFIKI_FLASH_BLOCK_K", "128"))
    # ALL flash seqs before ANY xla attempt: one hung XLA compile at an
    # early seq must not cost the later flash rows too
    for kind in ("flash", "xla"):
        for s in seqs:
            inner = (mha_reference if kind == "xla" else functools.partial(
                flash_attention, block_q=block_q, block_k=block_k))

            def loss(q, k, v):
                return inner(q, k, v).astype(jnp.float32).sum()

            def multi(q, k, v):
                # n_steps grad computations in ONE dispatch (the tunnel
                # adds ~15-20 ms per dispatch; see module docstring) —
                # the tiny grad-scaled update forces each iteration to
                # depend on the last so XLA cannot collapse the scan
                def body(c, _):
                    g = jax.grad(loss)(c, k, v)
                    return c + g.astype(c.dtype) * 1e-9, ()

                c, _ = lax.scan(body, q, None, length=n_steps)
                return c.astype(jnp.float32).sum()

            jitted = jax.jit(multi)
            shape = (b, h, s, dh)
            ks = jax.random.split(jax.random.key(0), 3)
            q, k, v = (jax.random.normal(kk, shape, jnp.bfloat16)
                       for kk in ks)
            tag = {"seq": s, "kind": kind, "batch": b, "heads": h,
                   "dh": dh,
                   "block_q": block_q if kind == "flash" else None,
                   "block_k": block_k if kind == "flash" else None}
            try:
                _ = float(jitted(q, k, v))  # compile + warmup, fenced
                t0 = time.perf_counter()
                _ = float(jitted(q, k, v))
                wall = time.perf_counter() - t0
            except Exception as e:
                print(json.dumps({**tag, "error": repr(e)[:300]}),
                      flush=True)
                continue
            print(json.dumps({
                **tag,
                "ms_per_step": round(wall / n_steps * 1000, 2),
                "backend": jax.default_backend(),
            }), flush=True)


def bench_ablation() -> None:
    """ViT-B/16 step-time COST ATTRIBUTION (not a tuning sweep): where
    does the gap between measured MFU (~0.36) and peak go? One JSON line
    per variant so a mid-run hang loses nothing. The first two rows
    calibrate the ACHIEVABLE peak — if a chained square bf16 GEMM cannot
    approach 197 TFLOP/s through this chip/tunnel, every MFU in the
    record should be read against the calibrated ceiling, not the
    datasheet. Then: fwd-only vs fwd+bwd vs full step splits compute
    between forward, backward(+remat recompute), and optimizer;
    remat=None at batches that fit without remat prices the recompute;
    forced-flash prices the attention kernel choice at seq 196."""
    import dataclasses

    import jax
    import jax.numpy as jnp
    import optax

    from rafiki_tpu.models import vit

    peak = PEAK_TFLOPS * 1e12

    def gemm(tag, make_operands, chain_body, flops, iters=24):
        try:
            ops = make_operands()

            def chain(*ops):
                c, _ = jax.lax.scan(lambda c, _: (chain_body(c, *ops[1:]), ()),
                                    ops[0], None, length=iters)
                return c

            jitted = jax.jit(chain)
            c = jitted(*ops)
            _ = float(jnp.sum(c.astype(jnp.float32)))
            t0 = time.perf_counter()
            c = jitted(*ops)
            _ = float(jnp.sum(c.astype(jnp.float32)))
            dt = time.perf_counter() - t0
            print(json.dumps({
                "tag": tag, "tflops_per_s": round(flops * iters / dt / 1e12, 1),
                "pct_of_peak": round(flops * iters / dt / peak * 100, 1),
                "backend": jax.default_backend()}), flush=True)
        except Exception as e:
            print(json.dumps({"tag": tag, "error": repr(e)[:200]}), flush=True)

    # CPU backend (or RAFIKI_ABLATE_SMALL=1) = tiny smoke of every
    # variant's trace path: a trace error must surface before the run
    # spends a TPU window, and a CPU box must never attempt 8192-cube
    # GEMMs. Same falsy rule as __main__'s RAFIKI_BENCH_SMALL.
    small = (jax.default_backend() == "cpu"
             or os.environ.get("RAFIKI_ABLATE_SMALL", "").strip().lower()
             not in ("", "0", "false"))
    n = 256 if small else 8192
    gemm(f"gemm_calibration_{n}",
         lambda: (jax.random.normal(jax.random.key(0), (n, n), jnp.bfloat16),
                  jax.random.normal(jax.random.key(1), (n, n), jnp.bfloat16)),
         lambda c, b: c @ b, 2.0 * n * n * n)
    m, k, nn = (256, 64, 128) if small else (192 * 196, 768, 3072)
    gemm("gemm_vit_proj_shape",
         lambda: (jax.random.normal(jax.random.key(2), (m, k), jnp.bfloat16),
                  jax.random.normal(jax.random.key(3), (k, nn), jnp.bfloat16)),
         lambda c, w: (c @ w)[:, :k], 2.0 * m * k * nn)

    def mkcfg(remat, unroll=1, flash=None):
        cfg = (vit.tiny(image_size=32) if small
               else vit.vit_b16(num_classes=1000, image_size=224))
        return dataclasses.replace(cfg, encoder=dataclasses.replace(
            cfg.encoder, remat=remat, scan_unroll=unroll, use_flash=flash))

    def run(tag, cfg, batch, steps_per_call=8, n_steps=32, mode="full",
            flops_mult=3.0):
        params = jax.jit(lambda r: vit.init(r, cfg))(jax.random.key(0))
        opt = optax.adamw(1e-3)
        opt_state = jax.jit(opt.init)(params)
        x = jnp.zeros((batch, cfg.image_size, cfg.image_size, 3),
                      jnp.bfloat16)
        y = jnp.zeros((batch,), jnp.int32)

        def loss_fn(p, rng):
            logits = vit.apply(p, x, cfg, rng, deterministic=False)
            return optax.softmax_cross_entropy_with_integer_labels(
                logits, y).mean()

        if mode == "fwd":
            def multi(p, s, rng):
                def one(carry, _):
                    acc, r = carry
                    r = jax.random.split(r)[0]
                    # accumulate the real loss — a *0 here would let XLA
                    # dead-code-eliminate the whole forward
                    return (acc + loss_fn(p, r), r), acc
                (acc, rng), _ = jax.lax.scan(
                    one, (jnp.zeros(()), rng), None, length=steps_per_call)
                return p, s, rng, acc
        else:  # "grad"
            def multi(p, s, rng):
                def one(carry, _):
                    pp, r = carry
                    r, sub = jax.random.split(r)
                    loss, g = jax.value_and_grad(loss_fn)(pp, sub)
                    # consume the grads without an optimizer: a non-zero
                    # scale keeps XLA from dead-code-eliminating backward
                    pp = jax.tree.map(
                        lambda a, b: a - jnp.asarray(1e-30, a.dtype)
                        * b.astype(a.dtype), pp, g)
                    return (pp, r), loss
                (p, rng), ls = jax.lax.scan(one, (p, rng), None,
                                            length=steps_per_call)
                return p, s, rng, ls[-1]

        jitted = jax.jit(multi, donate_argnums=(0, 1))
        rng = jax.random.key(1)
        try:
            params, opt_state, rng, out = jitted(params, opt_state, rng)
            _ = float(jnp.sum(out))
            n_calls = max(n_steps // steps_per_call, 1)
            t0 = time.perf_counter()
            for _ in range(n_calls):
                params, opt_state, rng, out = jitted(params, opt_state, rng)
            _ = float(jnp.sum(out))
            dt = (time.perf_counter() - t0) / (n_calls * steps_per_call)
        except Exception as e:
            print(json.dumps({"tag": tag, "error": repr(e)[:200]}),
                  flush=True)
            return
        fl = vit_train_flops(cfg, batch) * flops_mult / 3.0
        print(json.dumps({
            "tag": tag, "batch": batch, "mode": mode,
            "step_ms": round(dt * 1000, 2),
            "eff_mfu": round(fl / (dt * peak), 4),
            "imgs_per_s": round(batch / dt, 1),
            "backend": jax.default_backend()}), flush=True)

    def full(tag, **kwargs):
        # full-step rows delegate to bench_vit — ONE timing harness for
        # the fused train step, so ablation rows stay comparable to the
        # sweep's and cannot drift from it
        if small:
            kwargs = {**kwargs, "batch_size": 4, "image_size": 64,
                      "n_steps": 4, "steps_per_call": 2}
        try:
            r = bench_vit(**kwargs)
        except Exception as e:
            print(json.dumps({"tag": tag, "error": repr(e)[:200]}),
                  flush=True)
            return
        print(json.dumps({"tag": tag, **{k: r[k] for k in (
            "batch_size", "remat", "use_flash", "steps_per_call",
            "step_time_ms", "images_per_s", "mfu", "backend")}}),
            flush=True)

    B = 4 if small else 192
    steps = dict(steps_per_call=2, n_steps=4) if small else {}
    full("full_dots", batch_size=192, remat="dots")
    full("full_dots_spc16", batch_size=192, remat="dots", steps_per_call=16)
    run("fwd_dots", mkcfg("dots"), B, mode="fwd", flops_mult=1.0, **steps)
    run("grad_dots", mkcfg("dots"), B, mode="grad", **steps)
    run("fwd_none", mkcfg(None), B, mode="fwd", flops_mult=1.0, **steps)
    for b in ((8,) if small else (64, 96, 128)):
        full(f"full_none_b{b}", batch_size=b, remat=None)
        full(f"full_dots_b{b}", batch_size=b, remat="dots")
    full("full_full_b192", batch_size=192, remat="full")
    full("full_dots_flash", batch_size=192, remat="dots", use_flash=True)


def bench_int8(batches=(1, 8, 64), seq: int = 128, n_calls: int = 30) -> None:
    """Weight-only int8 serving delta in the regime it targets: a
    weight-bandwidth-bound predict (BERT-base, ~110M params — each
    small-batch call streams every kernel out of HBM while the MXU
    idles). The end-to-end bench measures the delta on its small CNN,
    where dequant overhead dominates and int8 LOSES (BENCH_r05
    int8_unloaded_speedup ~0.8); this is the companion measurement on a
    model the feature is actually for, per batch size. One JSON line per
    (batch, mode)."""
    import jax
    import jax.numpy as jnp

    from rafiki_tpu.models import bert
    from rafiki_tpu.sdk.quant import dequantize_pytree, quantize_pytree

    cfg = bert.bert_base(num_classes=2)
    params = jax.jit(lambda r: bert.init(r, cfg))(jax.random.key(0))
    # serving keeps bf16 masters; the int8 copy is quantized from them
    params = jax.tree.map(lambda a: a.astype(jnp.bfloat16)
                          if a.dtype == jnp.float32 else a, params)
    qparams = jax.device_put(quantize_pytree(params))

    def predict(p, ids):
        return jax.nn.softmax(bert.apply(p, ids, cfg), axis=-1)

    def predict_q(qp, ids):
        return jax.nn.softmax(
            bert.apply(dequantize_pytree(qp), ids, cfg), axis=-1)

    for batch in batches:
        ids = jnp.zeros((batch, seq), jnp.int32)
        base_wall = None
        for mode, fn, p in (("bf16", predict, params),
                            ("int8", predict_q, qparams)):
            jitted = jax.jit(fn)
            try:
                _ = np.asarray(jitted(p, ids))  # compile + fence
                t0 = time.perf_counter()
                for _ in range(n_calls):
                    out = jitted(p, ids)
                _ = np.asarray(out)  # one fence: per-call overhead stays in
                wall = (time.perf_counter() - t0) / n_calls
            except Exception as e:
                print(json.dumps({"model": "BERT-base", "batch": batch,
                                  "mode": mode, "error": repr(e)[:300]}),
                      flush=True)
                continue
            row = {"model": "BERT-base", "seq": seq, "batch": batch,
                   "mode": mode, "ms_per_call": round(wall * 1000, 2),
                   "backend": jax.default_backend()}
            if mode == "bf16":
                base_wall = wall
            elif base_wall:
                row["speedup_vs_bf16"] = round(base_wall / wall, 3)
            print(json.dumps(row), flush=True)


def sweep_vit() -> None:
    """Single-chip ViT tuning sweep (VERDICT r3 "next" #2): remat policy x
    batch x scan-unroll, one JSON line per config (so a crash mid-sweep
    loses nothing), best-by-MFU summary last. Grid via env:
    RAFIKI_SWEEP_BATCHES / RAFIKI_SWEEP_REMATS / RAFIKI_SWEEP_UNROLLS."""
    batches = [int(b) for b in os.environ.get(
        "RAFIKI_SWEEP_BATCHES", "128,192,256").split(",")]
    remats = [None if r in ("none", "") else r for r in os.environ.get(
        "RAFIKI_SWEEP_REMATS", "dots,none").split(",")]
    unrolls = [int(u) for u in os.environ.get(
        "RAFIKI_SWEEP_UNROLLS", "1,2,4").split(",")]
    # attention kernel at seq 197 (auto = footprint dispatch -> XLA fused;
    # flash forces the pallas kernel) and bf16 adamw first moment
    flashes = [{"auto": None, "flash": True, "xla": False}[f]
               for f in os.environ.get("RAFIKI_SWEEP_FLASH", "auto").split(",")]
    mus = [m == "bf16" for m in os.environ.get(
        "RAFIKI_SWEEP_MU", "f32,bf16").split(",")]
    qkvs = [q == "1" for q in os.environ.get(
        "RAFIKI_SWEEP_QKV", "0,1").split(",")]
    best = None
    for remat in remats:
        for unroll in unrolls:
            for flash in flashes:
                for mu in mus:
                    for qkv in qkvs:
                        for batch in batches:
                            tag = {"batch": batch, "remat": remat,
                                   "unroll": unroll, "flash": flash,
                                   "mu_bf16": mu, "fused_qkv": qkv}
                            try:
                                r = bench_vit(batch_size=batch, remat=remat,
                                              scan_unroll=unroll,
                                              use_flash=flash, mu_bf16=mu,
                                              fused_qkv=qkv)
                            except Exception as e:  # e.g. OOM without remat
                                print(json.dumps(
                                    {**tag, "error": repr(e)[:300]}),
                                    flush=True)
                                continue
                            print(json.dumps(
                                {**tag, "mfu": r["mfu"],
                                 "images_per_s": r["images_per_s"],
                                 "step_time_ms": r["step_time_ms"]}),
                                flush=True)
                            if best is None or r["mfu"] > best[1]["mfu"]:
                                best = (tag, r)
    if best is not None:
        print(json.dumps({"best": best[0], "result": best[1]}), flush=True)


if __name__ == "__main__":
    import sys

    import jax

    # "0"/"false"/"" (any case/whitespace) must NOT count as small
    small = (jax.default_backend() == "cpu"
             or os.environ.get("RAFIKI_BENCH_SMALL", "").strip().lower()
             not in ("", "0", "false"))
    if "--sweep-vit" in sys.argv:
        sweep_vit()
    elif "--sweep-pggan" in sys.argv:
        sweep_pggan()
    elif "--ablate" in sys.argv:
        bench_ablation()
    elif "--int8" in sys.argv:
        bench_int8(batches=(1, 4) if small else (1, 8, 64),
                   seq=32 if small else 128,
                   n_calls=3 if small else 30)
    elif "--longctx" in sys.argv:
        bench_longctx(seqs=(256, 512) if small else (2048, 4096, 8192),
                      n_steps=2 if small else 8)
    else:
        print(json.dumps(run_all(small=small), indent=2))
