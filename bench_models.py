"""Flagship-model benchmarks: step time, throughput, and MFU on the live
backend.

Fills the BASELINE.md "Measured TPU baselines" rows the AutoML bench can't:
ViT-B/16 (the BASELINE.json north-star config) and the progressive GAN (the
reference fork's marquee model, reference pg_gans.py). FLOPs come from
XLA's own cost analysis of the compiled step (falling back to an analytic
transformer estimate), so

    MFU = program_flops / (step_time * peak_flops)

is the compiler's count, not a hand-wave. Peak chip flops defaults to the
v5e bf16 number and is overridable with RAFIKI_PEAK_TFLOPS.

Run standalone (`python bench_models.py`) for a JSON report, or let
bench.py embed the numbers in its one-line summary (RAFIKI_BENCH_MODELS=0
skips).
"""

from __future__ import annotations

import json
import os
import time
from typing import Any, Dict, Optional, Tuple

import numpy as np

# v5e: 197 TFLOP/s bf16 per chip (public spec); override for other parts
PEAK_TFLOPS = float(os.environ.get("RAFIKI_PEAK_TFLOPS", "197"))


def _compiled_flops(jitted, *args) -> Optional[float]:
    """XLA's own FLOP estimate for the compiled program (None if the
    backend doesn't report one)."""
    try:
        compiled = jitted.lower(*args).compile()
        analysis = compiled.cost_analysis()
        if isinstance(analysis, list):  # per-device list on some backends
            analysis = analysis[0]
        flops = float(analysis.get("flops", 0.0))
        return flops if flops > 0 else None
    except Exception:
        return None


def _time_steps(run_step, n_steps: int) -> float:
    """Median wall-clock seconds per step (run_step must block on device)."""
    times = []
    for _ in range(n_steps):
        t0 = time.perf_counter()
        run_step()
        times.append(time.perf_counter() - t0)
    return float(np.median(times))


def bench_vit(batch_size: int = 64, image_size: int = 224,
              n_steps: int = 20) -> Dict[str, Any]:
    """ViT-B/16 fused train step (fwd+bwd+adamw), bf16 activations."""
    import jax
    import jax.numpy as jnp
    import optax

    from rafiki_tpu.models import vit

    cfg = vit.vit_b16(num_classes=1000, image_size=image_size)
    params = jax.jit(lambda r: vit.init(r, cfg))(jax.random.key(0))
    opt = optax.adamw(1e-3)
    opt_state = jax.jit(opt.init)(params)

    def loss_fn(p, batch, rng):
        x, y = batch
        logits = vit.apply(p, x, cfg, rng, deterministic=False)
        return optax.softmax_cross_entropy_with_integer_labels(logits, y).mean()

    @jax.jit
    def train_step(p, s, batch, rng):
        loss, grads = jax.value_and_grad(loss_fn)(p, batch, rng)
        updates, s = opt.update(grads, s, p)
        return optax.apply_updates(p, updates), s, loss

    x = jnp.zeros((batch_size, image_size, image_size, 3), jnp.float32)
    y = jnp.zeros((batch_size,), jnp.int32)
    rng = jax.random.key(1)

    flops = _compiled_flops(train_step, params, opt_state, (x, y), rng)
    # warmup (compile + first dispatch)
    params, opt_state, loss = train_step(params, opt_state, (x, y), rng)
    jax.block_until_ready(loss)

    state = {"p": params, "s": opt_state}

    def one():
        state["p"], state["s"], loss = train_step(
            state["p"], state["s"], (x, y), rng)
        jax.block_until_ready(loss)

    step_s = _time_steps(one, n_steps)
    out = {
        "model": "ViT-B/16",
        "batch_size": batch_size,
        "step_time_ms": round(step_s * 1000, 2),
        "steps_per_s": round(1.0 / step_s, 3),
        "images_per_s": round(batch_size / step_s, 1),
        "backend": jax.default_backend(),
    }
    if flops is not None:
        out["step_tflops"] = round(flops / 1e12, 3)
        out["mfu"] = round(flops / (step_s * PEAK_TFLOPS * 1e12), 4)
    return out


def bench_pggan(resolution: int = 64, minibatch: int = 64,
                n_steps: int = 20) -> Dict[str, Any]:
    """Progressive-GAN D+G step at full resolution (the steady-state cost
    once growth completes — the reference's headline img/s regime)."""
    import jax
    import jax.numpy as jnp

    from rafiki_tpu.models import pggan

    cfg = pggan.PgganConfig(resolution=resolution)
    trainer = pggan.PgganTrainer(cfg)
    trainer.init_optimizers(1e-3, 1e-3)
    max_stage = cfg.num_stages - 1
    d_step, g_step = trainer._get_steps(max_stage, minibatch)
    reals = jnp.zeros((minibatch, resolution, resolution, 3), jnp.float32)
    lod = jnp.float32(0.0)
    state = {"rng": jax.random.PRNGKey(0)}

    def one():
        state["rng"], kd, kg = jax.random.split(state["rng"], 3)
        trainer.d_params, trainer._opt_state["d"], d_loss, _ = d_step(
            trainer.d_params, trainer.g_params, trainer._opt_state["d"],
            reals, None, lod, kd)
        trainer.g_params, trainer._opt_state["g"], g_loss = g_step(
            trainer.g_params, trainer.d_params, trainer._opt_state["g"],
            None, lod, kg)
        jax.block_until_ready(g_loss)

    one()  # warmup: compiles both D and G directions
    step_s = _time_steps(one, n_steps)
    return {
        "model": f"PGGAN-{resolution}",
        "minibatch": minibatch,
        "step_time_ms": round(step_s * 1000, 2),
        "images_per_s": round(minibatch / step_s, 1),
        "kimg_per_hour": round(minibatch / step_s * 3.6, 1),
        "backend": jax.default_backend(),
    }


def run_all(small: bool = False) -> Dict[str, Any]:
    """All flagship benches; ``small`` shrinks shapes for CPU smoke."""
    if small:
        return {
            "vit": bench_vit(batch_size=4, image_size=64, n_steps=3),
            "pggan": bench_pggan(resolution=16, minibatch=8, n_steps=3),
        }
    return {
        "vit": bench_vit(),
        "pggan": bench_pggan(),
    }


if __name__ == "__main__":
    import jax

    small = jax.default_backend() == "cpu" or bool(
        os.environ.get("RAFIKI_BENCH_SMALL"))
    print(json.dumps(run_all(small=small), indent=2))
